"""rplint rule engine: file walking, AST parsing, suppression and
baseline bookkeeping shared by every rule.

A rule is an object with:
  code     -- "RPL00x"
  name     -- short slug for --list-rules
  check(ctx) -> iterable[Finding]

`ctx` is a ModuleContext: one parsed file plus the helpers rules need
(qualname-aware function iteration, dotted-name resolution). Rules
never read the filesystem themselves — the engine owns IO so the whole
suite stays stdlib-only and trivially testable against tmp fixtures.
"""

from __future__ import annotations

import ast
import json
import os
import re
import tokenize
from dataclasses import dataclass, field

_SUPPRESS_RE = re.compile(r"#\s*rplint:\s*disable=([A-Z0-9,\s]+)")


class LintError(Exception):
    """Internal analyzer failure (exit code 2), as opposed to findings."""


@dataclass(frozen=True)
class Finding:
    path: str  # posix-style path relative to the scan root
    line: int  # 1-based line of the offending statement
    col: int
    rule: str
    message: str
    qualname: str = ""  # enclosing function, "" at module level
    # race-rule payload (RPL015/016): the attribute and the guard sets
    # per site, so --format json is machine-triageable without parsing
    # the message
    attr: str = ""
    guards: tuple = ()  # ((label, (guard, ...)), ...)

    @property
    def key(self) -> str:
        """Baseline identity: line numbers drift, scopes rarely do."""
        return f"{self.path}::{self.qualname or '<module>'}::{self.rule}"

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "qualname": self.qualname,
            "attr": self.attr,
            "guards": {label: list(g) for label, g in self.guards},
            "message": self.message,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Finding":
        return cls(
            path=d["path"],
            line=d["line"],
            col=d["col"],
            rule=d["rule"],
            message=d["message"],
            qualname=d.get("qualname", ""),
            attr=d.get("attr", ""),
            guards=tuple(
                (label, tuple(g)) for label, g in d.get("guards", {}).items()
            ),
        )


@dataclass
class FunctionScope:
    qualname: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    is_async: bool
    parents: tuple = ()  # enclosing FunctionDef/ClassDef nodes, outermost first


@dataclass
class ModuleContext:
    path: str  # relative posix path
    abs_path: str
    tree: ast.Module
    source: str
    suppressions: dict[int, set[str]]  # line -> rules disabled there
    _functions: list[FunctionScope] = field(default_factory=list)

    def functions(self) -> list[FunctionScope]:
        if not self._functions:
            self._collect(self.tree, prefix="", parents=())
        return self._functions

    def _collect(self, node: ast.AST, prefix: str, parents: tuple) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{prefix}{child.name}"
                self._functions.append(
                    FunctionScope(
                        qualname=qn,
                        node=child,
                        is_async=isinstance(child, ast.AsyncFunctionDef),
                        parents=parents,
                    )
                )
                self._collect(child, prefix=qn + ".", parents=parents + (child,))
            elif isinstance(child, ast.ClassDef):
                self._collect(
                    child, prefix=f"{prefix}{child.name}.", parents=parents + (child,)
                )
            else:
                self._collect(child, prefix=prefix, parents=parents)

    def suppressed(self, node: ast.AST, rule: str) -> bool:
        """True if any line spanned by `node` carries a disable comment
        for `rule` (so the comment can sit on any line of a multi-line
        statement, including the closing paren)."""
        start = getattr(node, "lineno", 0)
        end = getattr(node, "end_lineno", start)
        for line in range(start, end + 1):
            if rule in self.suppressions.get(line, ()):
                return True
        return False


def dotted_name(node: ast.AST) -> str:
    """Best-effort dotted name of an expression: `np.maximum.at` ->
    "np.maximum.at", `touch` -> "touch". Unresolvable parts (calls,
    subscripts) contribute "?" so callers can still suffix-match."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return f"{dotted_name(node.value)}.{node.attr}"
    if isinstance(node, ast.Call):
        return f"{dotted_name(node.func)}()"
    if isinstance(node, ast.Subscript):
        return f"{dotted_name(node.value)}[]"
    return "?"


def _collect_suppressions(source: str) -> dict[int, set[str]]:
    out: dict[int, set[str]] = {}
    try:
        tokens = tokenize.generate_tokens(iter(source.splitlines(True)).__next__)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if m:
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                out.setdefault(tok.start[0], set()).update(rules)
    except tokenize.TokenError:
        pass  # parse errors surface via ast.parse instead
    return out


def parse_module(
    abs_path: str, rel_path: str, source: str | None = None
) -> ModuleContext:
    try:
        if source is None:
            with open(abs_path, "r", encoding="utf-8") as f:
                source = f.read()
        tree = ast.parse(source, filename=rel_path)
    except (OSError, SyntaxError, ValueError) as e:
        raise LintError(f"{rel_path}: cannot parse: {e}") from e
    return ModuleContext(
        path=rel_path,
        abs_path=abs_path,
        tree=tree,
        source=source,
        suppressions=_collect_suppressions(source),
    )


def iter_python_files(paths: list[str]) -> list[tuple[str, str]]:
    """(abs_path, rel_path) for every .py under `paths`, rel to cwd
    when possible so finding keys are stable across machines."""
    out: list[tuple[str, str]] = []
    cwd = os.getcwd()

    def rel(p: str) -> str:
        ap = os.path.abspath(p)
        try:
            r = os.path.relpath(ap, cwd)
        except ValueError:  # different drive (windows)
            return ap.replace(os.sep, "/")
        return (ap if r.startswith("..") else r).replace(os.sep, "/")

    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                out.append((os.path.abspath(path), rel(path)))
            continue
        if not os.path.isdir(path):
            raise LintError(f"no such file or directory: {path}")
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(
                d for d in dirs if d not in ("__pycache__", ".git", "build")
            )
            for name in sorted(files):
                if name.endswith(".py"):
                    full = os.path.join(root, name)
                    out.append((os.path.abspath(full), rel(full)))
    return out


def default_rules() -> list:
    from .rules import ALL_RULES

    return [cls() for cls in ALL_RULES]


def _analyze_file(
    abs_path: str, rel_path: str, use_cache: bool
) -> tuple[dict, list[dict]]:
    """Per-file unit of work (also the multiprocessing worker body):
    pass-1 summary + findings of the FULL default per-file rule set,
    both as plain dicts. Cached by content hash when `use_cache`."""
    from . import cache as cache_mod
    from .program import summarize_module

    try:
        with open(abs_path, "rb") as f:
            content = f.read()
    except OSError as e:
        raise LintError(f"{rel_path}: cannot read: {e}") from e
    key = ""
    if use_cache:
        key = cache_mod.entry_key(rel_path, content)
        payload = cache_mod.load(key)
        if payload is not None:
            return payload["summary"], payload["findings"]
    try:
        source = content.decode("utf-8")
    except UnicodeDecodeError as e:
        raise LintError(f"{rel_path}: cannot decode: {e}") from e
    ctx = parse_module(abs_path, rel_path, source=source)
    findings: list[Finding] = []
    for rule in default_rules():
        if getattr(rule, "whole_program", False):
            continue
        findings.extend(rule.check(ctx) or ())
    summary = summarize_module(ctx).to_dict()
    findings_d = [f.to_dict() for f in findings]
    if use_cache:
        cache_mod.store(key, {"summary": summary, "findings": findings_d})
    return summary, findings_d


def _analyze_worker(args: tuple) -> tuple[dict, list[dict]]:
    return _analyze_file(*args)


def run_paths(
    paths: list[str],
    rules: list | None = None,
    jobs: int = 0,
    cache: bool = False,
) -> list[Finding]:
    """Lint every python file under `paths`; returns raw findings
    (suppressions applied, baseline NOT applied).

    Two passes: per-file rules run against each module's AST; rules
    marked `whole_program = True` run once, afterwards, over the
    ProgramIndex of pass-1 summaries (tools/rplint/program.py).

    `cache`/`jobs` take the batch path, which always evaluates the
    full default per-file rule set (then filters to the requested
    codes) so cache entries are rule-subset independent; custom rule
    objects outside the registry need the default serial path."""
    if rules is None:
        rules = default_rules()
    file_rules = [r for r in rules if not getattr(r, "whole_program", False)]
    prog_rules = [r for r in rules if getattr(r, "whole_program", False)]
    files = iter_python_files(paths)
    findings: list[Finding] = []
    summaries: list = []

    if cache or jobs > 1:
        from .program import FileSummary

        want = {r.code for r in file_rules}
        work = [(a, r, cache) for a, r in files]
        if jobs > 1 and len(work) > 1:
            import concurrent.futures as cf

            with cf.ProcessPoolExecutor(max_workers=jobs) as pool:
                results = list(pool.map(_analyze_worker, work, chunksize=8))
        else:
            results = [_analyze_file(*w) for w in work]
        for summary_d, file_findings in results:
            if prog_rules:
                summaries.append(FileSummary.from_dict(summary_d))
            findings.extend(
                Finding.from_dict(d)
                for d in file_findings
                if d["rule"] in want
            )
    else:
        from .program import summarize_module

        for abs_path, rel_path in files:
            ctx = parse_module(abs_path, rel_path)
            for rule in file_rules:
                findings.extend(rule.check(ctx) or ())
            if prog_rules:
                summaries.append(summarize_module(ctx))

    if prog_rules:
        from .program import ProgramIndex

        program = ProgramIndex(summaries)
        for rule in prog_rules:
            findings.extend(rule.check_program(program))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


# -- baseline ----------------------------------------------------------

BASELINE_PATH = os.path.join(os.path.dirname(__file__), "baseline.json")


def load_baseline(path: str | None = None) -> dict[str, int]:
    path = path or BASELINE_PATH
    if not os.path.exists(path):
        return {}
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise LintError(f"baseline {path}: {e}") from e
    entries = data.get("entries", {})
    if not isinstance(entries, dict):
        raise LintError(f"baseline {path}: 'entries' must be an object")
    return {str(k): int(v) for k, v in entries.items()}


def save_baseline(findings: list[Finding], path: str | None = None) -> None:
    counts: dict[str, int] = {}
    for f in findings:
        counts[f.key] = counts.get(f.key, 0) + 1
    path = path or BASELINE_PATH
    with open(path, "w", encoding="utf-8") as f:
        json.dump(
            {"version": 1, "entries": dict(sorted(counts.items()))},
            f,
            indent=2,
        )
        f.write("\n")


def apply_baseline(
    findings: list[Finding], baseline: dict[str, int]
) -> list[Finding]:
    """Subtract baselined counts per key; the excess (new findings in
    that scope) is reported. Reported findings within a key are the
    LAST ones by line — newly added code tends to sit below old."""
    by_key: dict[str, list[Finding]] = {}
    for f in findings:
        by_key.setdefault(f.key, []).append(f)
    out: list[Finding] = []
    for key, group in by_key.items():
        allowed = baseline.get(key, 0)
        if len(group) > allowed:
            out.extend(group[allowed:])
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out
