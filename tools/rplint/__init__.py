"""rplint — project-specific AST invariant checker for redpanda_tpu.

Static analysis over the codebase's correctness-by-convention
contracts, the review-time complement to the RP_SAME_DEBUG runtime
fingerprint (raft/shard_state.py):

  RPL001  SAME-lane writes must bump mut_epoch via touch()
  RPL002  host-sync (device materialization) forbidden in hot paths
  RPL003  jit-compiled functions must be pure
  RPL004  blocking calls forbidden inside async bodies (rpc/raft/admin)
  RPL005  broad except in async code must not swallow CancelledError

Stdlib-only (ast + tokenize): importable everywhere the repo is, with
no jax/numpy import cost — `python -m tools.rplint redpanda_tpu/`.

Suppressions: `# rplint: disable=RPL001` (comma-separated rule list)
anywhere on the lines spanned by the offending statement.

Baseline: tools/rplint/baseline.json maps `path::qualname::rule` keys
to counts; `--baseline` subtracts it (the gate ratchets — new findings
in a baselined scope still fail), `--update-baseline` rewrites it.
"""

from .engine import Finding, LintError, load_baseline, run_paths  # noqa: F401

__all__ = ["Finding", "LintError", "load_baseline", "run_paths"]
