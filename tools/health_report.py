#!/usr/bin/env python
"""Operator CLI for the partition-health plane.

Fetches `GET /v1/cluster/partition_health` from a broker's admin
endpoint and renders the bounded report: aggregate counters, the
shard/NTP load-skew bars, top-k laggy and hot partition tables, and
the cumulative lag distribution. `--json` emits the raw document
instead (pipe it to a file and replay it offline later with
`python tools/log_viewer.py --health dump.json` — same renderer).

`--alerts` additionally fetches `GET /v1/alerts` and appends the
burn-rate SLO section: rule thresholds, firing alerts with their burn
bars / hot NTPs / captured profile stacks, and the recently-cleared
tail. A saved alerts dump replays offline with
`python tools/log_viewer.py --alerts dump.json` — same renderer.

Usage:
    python tools/health_report.py [ADDR] [--top-k N] [--json] [--alerts]

ADDR defaults to 127.0.0.1:9644.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_BAR_WIDTH = 30


def _fetch(addr: str, path: str) -> dict:
    import http.client

    host, _, port = addr.partition(":")
    conn = http.client.HTTPConnection(host, int(port or 9644), timeout=10)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        body = resp.read()
        if resp.status != 200:
            raise SystemExit(
                f"health_report: {addr} returned {resp.status}: "
                f"{body[:200]!r}"
            )
        return json.loads(body)
    finally:
        conn.close()


def _fmt_bps(v: float) -> str:
    for unit in ("B/s", "KB/s", "MB/s", "GB/s"):
        if abs(v) < 1024.0 or unit == "GB/s":
            return f"{v:.1f} {unit}"
        v /= 1024.0
    return f"{v:.1f} GB/s"


def _skew_bar(skew: float, cap: float = 8.0) -> str:
    """Bar from 1.0 (balanced) to `cap`x (saturated): ops eyeball the
    imbalance without reading the number first."""
    frac = min(max(skew - 1.0, 0.0) / (cap - 1.0), 1.0)
    n = round(frac * _BAR_WIDTH)
    return "[" + "#" * n + "." * (_BAR_WIDTH - n) + f"] {skew:.2f}x"


def render_report(rep: dict, out=None) -> None:
    """Human rendering of one partition_health document (live fetch or
    an offline --json dump; log_viewer --health reuses this)."""
    out = out if out is not None else sys.stdout
    p = lambda s="": print(s, file=out)  # noqa: E731
    node = rep.get("node_id", "?")
    shards = rep.get("shards", 1)
    p(f"partition health @ node {node} ({shards} shard(s))")
    p(f"  active partitions   {rep.get('active', 0)}")
    p(f"  max follower lag    {rep.get('max_follower_lag', 0)} entries")
    p(f"  under-replicated    {rep.get('under_replicated', 0)}")
    p(f"  leaderless          {rep.get('leaderless', 0)}")
    rates = rep.get("rates") or {}
    p(
        "  load                "
        + "  ".join(
            f"{k.removesuffix('_bps')} {_fmt_bps(rates.get(k, 0.0))}"
            for k in ("produce_bps", "fetch_bps", "append_bps", "total_bps")
        )
    )
    p(f"  ntp skew            {_skew_bar(rep.get('skew', 1.0))}")
    if "shard_skew" in rep:
        p(f"  shard skew          {_skew_bar(rep.get('shard_skew', 1.0))}")
    rp = rep.get("read_path") or {}
    if rp:
        # fetch-plane cache effectiveness: wire-plane hits serve with
        # zero decode/re-encode; decoded hits pay one conversion; a
        # reader hit resumes a positioned segment scan mid-file
        def _ratio(hits, misses):
            total = hits + misses
            return f"{hits / total * 100:5.1f}%" if total else "    -"

        p(
            "  fetch cache         "
            f"wire {_ratio(rp.get('wire_hits', 0), rp.get('wire_misses', 0))}"
            f"  decoded {_ratio(rp.get('cache_hits', 0), rp.get('cache_misses', 0))}"
            f"  readers {_ratio(rp.get('reader_hits', 0), rp.get('reader_misses', 0))}"
        )

    laggy = rep.get("top_laggy") or []
    if laggy:
        p()
        p(f"top laggy partitions ({len(laggy)}):")
        w = max(len(str(r.get("key", "?"))) for r in laggy)
        for r in laggy:
            shard = f"  shard={r['shard']}" if "shard" in r else ""
            under = "  UNDER-REPLICATED" if r.get("under_replicated") else ""
            p(
                f"  {str(r.get('key', '?')):<{w}}  group={r.get('group')}"
                f"  lag={r.get('lag')}{shard}{under}"
            )

    hot = rep.get("top_hot") or []
    if hot:
        p()
        p(f"top hot partitions ({len(hot)}):")
        w = max(len(str(r.get("key", "?"))) for r in hot)
        peak = max(r.get("total_bps", 0.0) for r in hot) or 1.0
        for r in hot:
            n = round(r.get("total_bps", 0.0) / peak * _BAR_WIDTH)
            shard = f"  shard={r['shard']}" if "shard" in r else ""
            p(
                f"  {str(r.get('key', '?')):<{w}}  "
                f"{'#' * n:<{_BAR_WIDTH}}  "
                f"{_fmt_bps(r.get('total_bps', 0.0))}{shard}"
            )

    hist = rep.get("lag_histogram") or []
    edges = rep.get("lag_bucket_edges")
    if edges is None and hist:
        from redpanda_tpu.observability.health import lag_bucket_edges

        edges = lag_bucket_edges()
    if hist and edges and hist[-1]:
        p()
        p(f"lag distribution ({hist[-1]} leader partitions, cumulative):")
        prev = 0
        for edge, cum in zip(edges, hist):
            in_bucket = cum - prev
            prev = cum
            if not in_bucket:
                continue
            n = round(in_bucket / hist[-1] * _BAR_WIDTH)
            p(f"  lag <= {edge:>6}  {'#' * n:<{_BAR_WIDTH}}  {in_bucket}")


def _burn_bar(burn: float, cap: float = 4.0) -> str:
    """Bar from 0 (healthy) to `cap`x the SLO threshold; 1.0 is the
    breach line, marked so the eye finds it."""
    frac = min(max(burn, 0.0) / cap, 1.0)
    n = round(frac * _BAR_WIDTH)
    mark = round(1.0 / cap * _BAR_WIDTH)
    bar = ["#" if i < n else "." for i in range(_BAR_WIDTH)]
    if 0 <= mark < _BAR_WIDTH:
        bar[mark] = "|"
    return "[" + "".join(bar) + f"] {burn:.2f}x"


def _fmt_wall(ts) -> str:
    if not ts:
        return "-"
    import datetime

    return datetime.datetime.fromtimestamp(
        ts, tz=datetime.timezone.utc
    ).strftime("%H:%M:%SZ")


def render_alerts(doc: dict, out=None) -> None:
    """Human rendering of one /v1/alerts document (live fetch or an
    offline dump; log_viewer --alerts reuses this)."""
    out = out if out is not None else sys.stdout
    p = lambda s="": print(s, file=out)  # noqa: E731
    if not doc.get("enabled", False):
        p("alerts: disabled (RP_ALERTS=0 or flight-data ring off)")
        return
    p(
        f"alerts @ slo profile '{doc.get('profile')}' "
        f"(fast {doc.get('fast_window_s')}s / slow {doc.get('slow_window_s')}s, "
        f"{doc.get('evaluations', 0)} evaluations)"
    )
    for r in doc.get("rules") or []:
        p(
            f"  rule {r.get('name'):<18} {r.get('kind'):<9} "
            f"threshold {r.get('threshold')} {r.get('unit', '')}".rstrip()
        )

    firing = doc.get("firing") or []
    p()
    if not firing:
        p("firing: none")
    else:
        p(f"firing ({len(firing)}):")
        for a in firing:
            burn = a.get("burn") or {}
            obs = (a.get("observed") or {}).get("fast") or {}
            p(
                f"  {a.get('name')}  since {_fmt_wall(a.get('fired_wall'))}"
                f"  observed {obs.get('value', 0):.6g}"
                f" > {(a.get('rule') or {}).get('threshold')}"
                f" {(a.get('rule') or {}).get('unit', '')}".rstrip()
            )
            p(f"    burn fast  {_burn_bar(burn.get('fast', 0.0))}")
            p(f"    burn slow  {_burn_bar(burn.get('slow', 0.0))}")
            for ntp in a.get("hot_ntps") or []:
                p(
                    f"    hot {str(ntp.get('key', '?')):<24} "
                    f"{_fmt_bps(ntp.get('total_bps', 0.0))}"
                )
            prof = a.get("profile") or {}
            for s in (prof.get("stacks") or [])[:5]:
                leaf = s.get("stack", "").rsplit(";", 2)
                p(
                    f"    prof {s.get('pct', 0):5.1f}%  "
                    + ";".join(leaf[-2:])
                )

    recent = doc.get("recent") or []
    if recent:
        p()
        p(f"recently cleared ({len(recent)}):")
        for a in recent:
            p(
                f"  {a.get('name')}  {_fmt_wall(a.get('fired_wall'))} -> "
                f"{_fmt_wall(a.get('cleared_wall'))} "
                f"({a.get('duration_s', 0):.1f}s)"
            )


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "addr",
        nargs="?",
        default="127.0.0.1:9644",
        help="admin HOST:PORT (default 127.0.0.1:9644)",
    )
    ap.add_argument("--top-k", type=int, default=10)
    ap.add_argument(
        "--json",
        action="store_true",
        help="emit the raw partition_health JSON instead of rendering",
    )
    ap.add_argument(
        "--alerts",
        action="store_true",
        help="also fetch /v1/alerts and append the burn-rate SLO section",
    )
    args = ap.parse_args(argv)
    rep = _fetch(args.addr, f"/v1/cluster/partition_health?top_k={args.top_k}")
    alerts = _fetch(args.addr, "/v1/alerts") if args.alerts else None
    if args.json:
        if alerts is not None:
            rep = {**rep, "alerts": alerts}
        json.dump(rep, sys.stdout, indent=2)
        print()
    else:
        render_report(rep)
        if alerts is not None:
            print()
            render_alerts(alerts)


if __name__ == "__main__":
    main()
