"""verify.sh front-end churn smoke: 1k raw kafka connections against
ONE in-process broker, torn down by RST storms, with the three
front-end planes asserted back to baseline after every storm:

  1. zero lost acked produces — every produce the broker acked is
     counted, across every churn round, with per-response decode;
  2. zero leaked protocol state — fetch sessions (count AND accounted
     bytes), per-client quota refs, and the pipelining inflight gauge
     all return to zero once the aborted connections drain;
  3. zero leaked tasks — the event-loop task count returns to the
     pre-storm baseline, so a stuck writer fiber or an orphaned
     read-loop can't hide behind a passing assertion.

The admin /metrics scrape cross-checks (2) from the outside: the
connection gauge the traffic bench grades must agree with the
server's own books.

Runs twice in tools/verify.sh: once with the native rp_frame_scan
framing leg, once with RP_NATIVE_FRAME=0 pinning the pure-Python
twin — a fallback framing regression can't hide behind a working .so.
Exit 0 = the front end survives connection churn in this environment.
The window/ordering/parity matrix lives in
tests/test_kafka_frontend.py; this is the "does a thousand-client
storm leak anything real" gate.
"""

import argparse
import asyncio
import json
import os
import shutil
import struct
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from redpanda_tpu.app import Broker, BrokerConfig  # noqa: E402
from redpanda_tpu.kafka.client import KafkaClient  # noqa: E402
from redpanda_tpu.kafka.protocol import FETCH, PRODUCE, Msg  # noqa: E402
from redpanda_tpu.kafka.protocol import produce_fast  # noqa: E402
from redpanda_tpu.kafka.protocol.headers import (  # noqa: E402
    RequestHeader,
    encode_request_header,
)
from redpanda_tpu.models.record import RecordBatchBuilder  # noqa: E402
from redpanda_tpu.rpc.loopback import LoopbackNetwork  # noqa: E402

TOPIC = "smoke"
N_PARTITIONS = 8


def _frame(api, version: int, corr: int, body: bytes) -> bytes:
    head = encode_request_header(
        RequestHeader(api.key, version, corr, None)
    )
    return struct.pack(">i", len(head) + len(body)) + head + body


async def _rpc(r, w, fr: bytes, corr: int) -> bytes:
    w.write(fr)
    (size,) = struct.unpack(">i", await r.readexactly(4))
    body = await r.readexactly(size)
    assert struct.unpack_from(">i", body)[0] == corr, "corr mismatch"
    return body


async def _settle(check, what: str, timeout: float = 10.0) -> None:
    loop = asyncio.get_event_loop()
    deadline = loop.time() + timeout
    while not check():
        if loop.time() > deadline:
            raise AssertionError(f"{what} did not settle in {timeout}s")
        await asyncio.sleep(0.02)


async def _open_many(host: str, port: int, n: int) -> list:
    out: list = []
    while len(out) < n:  # stay under the ~100 listen backlog
        k = min(100, n - len(out))
        out.extend(
            await asyncio.gather(
                *(asyncio.open_connection(host, port) for _ in range(k))
            )
        )
    return out


def _fetch_body(pid: int) -> bytes:
    return FETCH.encode_request(
        Msg(
            replica_id=-1,
            max_wait_ms=0,
            min_bytes=0,
            max_bytes=1 << 20,
            isolation_level=0,
            session_id=0,
            session_epoch=0,
            topics=[
                Msg(
                    topic=TOPIC,
                    partitions=[
                        Msg(
                            partition=pid,
                            current_leader_epoch=-1,
                            fetch_offset=0,
                            log_start_offset=-1,
                            partition_max_bytes=1 << 20,
                        )
                    ],
                )
            ],
            forgotten_topics_data=[],
            rack_id="",
        ),
        11,
    )


async def main(n_clients: int, rounds: int) -> None:
    tmp = tempfile.mkdtemp(prefix="rp_traffic_smoke_")
    b = Broker(
        BrokerConfig(
            node_id=0,
            data_dir=os.path.join(tmp, "n0"),
            members=[0],
            housekeeping_interval_s=0,
        ),
        loopback=LoopbackNetwork(),
    )
    await b.start()
    b.config.peer_kafka_addresses = {0: b.kafka_advertised}
    try:
        await b.wait_controller_leader()
        server = b.kafka_server
        boot = KafkaClient([b.kafka_advertised])
        await boot.create_topic(
            TOPIC, partitions=N_PARTITIONS, replication_factor=1
        )
        builder = RecordBatchBuilder()
        builder.add(b"v" * 64, key=b"k")
        wire = builder.build().to_kafka_wire()
        for pid in range(N_PARTITIONS):
            await boot.produce_wire(TOPIC, pid, wire, acks=1)
        await boot.close()
        host, port = b.kafka_advertised
        await _settle(lambda: len(server._conns) == 0, "boot teardown")
        task_base = len(asyncio.all_tasks())

        produce_bodies = [
            produce_fast.encode_request_single(
                7, False, None, 1, 10000, TOPIC, pid, wire
            )
            for pid in range(N_PARTITIONS)
        ]

        sent = acked = 0
        sessions_made = 0
        for _round in range(rounds):
            conns = await _open_many(host, port, n_clients)

            async def produce_one(i: int, r, w) -> None:
                nonlocal acked
                corr = 1_000_000 + i
                body = await _rpc(
                    r,
                    w,
                    _frame(
                        PRODUCE, 7, corr, produce_bodies[i % N_PARTITIONS]
                    ),
                    corr,
                )
                m = PRODUCE.decode_response(body[4:], 7)
                err = m.responses[0].partition_responses[0].error_code
                assert err == 0, f"produce error {err}"
                acked += 1

            for i in range(0, len(conns), 100):
                await asyncio.gather(
                    *(
                        produce_one(i + j, r, w)
                        for j, (r, w) in enumerate(conns[i : i + 100])
                    )
                )
            sent += len(conns)

            # a quarter of the fleet parks a real fetch session, so
            # the storm has per-connection protocol state to leak
            n_fetch = n_clients // 4

            async def establish(i: int, r, w) -> None:
                nonlocal sessions_made
                corr = 2_000_000 + i
                body = await _rpc(
                    r,
                    w,
                    _frame(FETCH, 11, corr, _fetch_body(i % N_PARTITIONS)),
                    corr,
                )
                (err,) = struct.unpack_from(">h", body, 8)
                (sid,) = struct.unpack_from(">i", body, 10)
                assert err == 0 and sid > 0, f"session declined {err}/{sid}"
                sessions_made += 1

            fetch_conns = conns[:n_fetch]
            for i in range(0, n_fetch, 100):
                await asyncio.gather(
                    *(
                        establish(i + j, r, w)
                        for j, (r, w) in enumerate(fetch_conns[i : i + 100])
                    )
                )

            assert len(server.fetch_sessions) == n_fetch, (
                len(server.fetch_sessions),
                n_fetch,
            )
            assert len(server._conns) == n_clients

            # the storm: every connection dies with an RST mid-state
            for r, w in conns:
                w.transport.abort()
            await _settle(
                lambda: len(server._conns) == 0, "storm teardown"
            )
            assert len(server.fetch_sessions) == 0
            assert server.fetch_sessions.mem_bytes() == 0
            assert server.quotas.live_state() == (0, 0, 0)
            assert server._inflight == 0
            # no orphaned read loops / writer fibers
            await _settle(
                lambda: len(asyncio.all_tasks()) <= task_base,
                "task count",
            )

        assert acked == sent, f"lost acked produce: {acked}/{sent}"
        assert sessions_made == rounds * (n_clients // 4)

        # outside view: the admin scrape agrees nothing is open
        if b.admin is not None:
            text = await asyncio.to_thread(
                lambda: urllib.request.urlopen(
                    f"http://127.0.0.1:{b.admin.port}/metrics", timeout=10
                )
                .read()
                .decode()
            )
            open_lines = [
                ln
                for ln in text.splitlines()
                if ln.startswith("redpanda_tpu_kafka_connections_open")
            ]
            assert open_lines, "connection gauge missing from /metrics"
            assert all(
                float(ln.rsplit(None, 1)[1]) == 0.0 for ln in open_lines
            ), open_lines
    finally:
        await b.stop()
        shutil.rmtree(tmp, ignore_errors=True)
    print(
        json.dumps(
            {
                "smoke": "traffic",
                "clients": n_clients,
                "rounds": rounds,
                "acked": acked,
                "fetch_sessions": sessions_made,
                "framing": "python"
                if os.environ.get("RP_NATIVE_FRAME") == "0"
                else "native",
            }
        )
    )
    print("TRAFFIC-SMOKE-OK")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=1000)
    ap.add_argument("--rounds", type=int, default=3)
    args = ap.parse_args()
    asyncio.run(main(args.clients, args.rounds))
