#!/usr/bin/env python
"""Offline log viewer: parse a broker data dir without a running node.

Reference: tools/offline_log_viewer — segment/kvstore/controller-log
decoding for debugging and forensics. Strictly read-only: segment
files are parsed from raw bytes (never opened for append), so the
viewer is safe to point at a LIVE broker's directory.

Usage:
    python tools/log_viewer.py DATA_DIR                    # overview
    python tools/log_viewer.py DATA_DIR --ntp kafka/t/0    # one log
    python tools/log_viewer.py DATA_DIR --controller       # raft0 cmds
    python tools/log_viewer.py DATA_DIR -v                 # + records
    python tools/log_viewer.py --traces traces.json        # waterfalls
    python tools/log_viewer.py --health health.json        # health dump
    python tools/log_viewer.py --alerts alerts.json        # SLO alerts

The --traces mode renders a flight-recorder dump (the JSON from
`GET /v1/debug/traces`, or a file of one tree per line) as aligned
per-request waterfalls: one row per span, indented by tree depth,
with a bar showing where the span sits inside its root's lifetime.

The --health mode replays a partition-health dump (the JSON from
`GET /v1/cluster/partition_health`, e.g. saved via
`tools/health_report.py --json`) through the same renderer the live
CLI uses: top-k laggy/hot tables, skew bars, lag distribution.

The --alerts mode does the same for a burn-rate SLO dump (the JSON
from `GET /v1/alerts`): rules, firing alerts with burn bars, hot NTPs
and captured profile stacks, recently-cleared tail.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from redpanda_tpu.models.record import (  # noqa: E402
    HEADER_SIZE,
    RecordBatch,
    RecordBatchHeader,
    RecordBatchType,
)


def iter_batches(path: str):
    """CRC-checked batch stream from one segment file (read-only raw
    parse — the log_replayer loop, minus recovery side effects)."""
    with open(path, "rb") as f:
        data = f.read()
    pos = 0
    while pos + HEADER_SIZE <= len(data):
        try:
            header = RecordBatchHeader.unpack(data[pos : pos + HEADER_SIZE])
        except Exception:
            yield pos, None, "unparseable header"
            return
        if header.size_bytes < HEADER_SIZE or pos + header.size_bytes > len(data):
            yield pos, None, "torn tail"
            return
        batch = RecordBatch(
            header, data[pos + HEADER_SIZE : pos + header.size_bytes]
        )
        note = "" if batch.verify_crc() else "CRC MISMATCH"
        yield pos, batch, note
        pos += header.size_bytes


def segments_of(log_dir: str) -> list[str]:
    segs = [f for f in os.listdir(log_dir) if f.endswith(".log")]
    return sorted(segs, key=lambda f: int(f.split("-")[0]))


def _preview(b: bytes | None, limit: int = 40) -> str:
    if b is None:
        return "null"
    try:
        s = b.decode("utf-8")
        printable = all(32 <= ord(ch) < 127 for ch in s)
    except UnicodeDecodeError:
        printable = False
    if printable and len(s) <= limit:
        return repr(s)
    return f"<{len(b)}B {b[:8].hex()}{'…' if len(b) > 8 else ''}>"


def dump_log(log_dir: str, verbose: bool, controller: bool = False) -> None:
    for seg in segments_of(log_dir):
        path = os.path.join(log_dir, seg)
        print(f"  segment {seg} ({os.path.getsize(path)} bytes)")
        for pos, batch, note in iter_batches(path):
            if batch is None:
                print(f"    @{pos}: {note}")
                continue
            h = batch.header
            btype = (
                RecordBatchType(h.type).name
                if h.type in RecordBatchType._value2member_map_
                else f"type{h.type}"
            )
            flag = f"  [{note}]" if note else ""
            print(
                f"    @{pos}: [{h.base_offset}..{h.last_offset}] "
                f"term={h.term} {btype} "
                f"{len(batch.body)}B records={h.record_count}{flag}"
            )
            if controller and h.type == RecordBatchType.topic_management_cmd:
                try:
                    from redpanda_tpu.cluster.commands import decode_commands

                    for ctype, cmd in decode_commands(batch):
                        print(f"        {ctype.name}: {cmd!r}")
                except Exception as e:
                    print(f"        <undecodable: {e}>")
            elif verbose:
                for r in batch.records():
                    print(
                        f"        +{r.offset_delta} key={_preview(r.key)} "
                        f"value={_preview(r.value)}"
                    )


def find_ntp_dirs(data_dir: str) -> dict[str, str]:
    """ntp string -> log dir for every partition under data/."""
    out = {}
    root = os.path.join(data_dir, "data")
    if not os.path.isdir(root):
        return out
    for ns in sorted(os.listdir(root)):
        for topic in sorted(os.listdir(os.path.join(root, ns))):
            tdir = os.path.join(root, ns, topic)
            for part in sorted(os.listdir(tdir), key=lambda p: int(p)):
                out[f"{ns}/{topic}/{part}"] = os.path.join(tdir, part)
    return out


# -- flight-recorder waterfalls (observability/trace.py dumps) ---------

_BAR_WIDTH = 40


def _fmt_tags(tags: dict | None) -> str:
    if not tags:
        return ""
    return " " + " ".join(f"{k}={v}" for k, v in sorted(tags.items()))


def _span_loc(s: dict) -> str:
    """shard/node provenance column for stitched fleet spans ("n0/s2");
    plain single-process dumps carry neither key and get no column."""
    if "shard" not in s and "node" not in s:
        return ""
    node = s.get("node", -1)
    shard = s.get("shard", "?")
    if isinstance(node, int) and node >= 0:
        return f"n{node}/s{shard}"
    return f"s{shard}"


def render_tree(tree: dict, out=None, slow: bool = False) -> None:
    """One aligned waterfall per span tree. Rows are sorted by start
    time; the bar column maps [root start, root end] onto a fixed
    width so sibling gaps (queue waits, flush coalescing) read as
    horizontal whitespace. Stitched fleet trees additionally get a
    shard/node provenance column, a `⇐origin` badge on each process
    hop's continuation root, and a header counting parts/shards.
    Parents living in a part that never reached the dump (orphans)
    simply render at depth 0 — missing links are expected, not fatal."""
    out = out if out is not None else sys.stdout
    spans = tree.get("spans", [])
    if not spans:
        return
    by_id = {s["id"]: s for s in spans}
    t0 = min(s["start_ns"] for s in spans)
    root_dur = max(tree.get("dur_ns", 0), 1)

    def depth(s: dict) -> int:
        d = 0
        while s.get("parent") and s["parent"] in by_id and d < 32:
            s = by_id[s["parent"]]
            d += 1
        return d

    flag = "  [SLOW]" if slow else ""
    extra = ""
    if tree.get("stitched"):
        extra = (
            f" stitched parts={tree.get('parts')}"
            f" shards={tree.get('shards')}"
        )
        if tree.get("orphaned"):
            extra += " [ORPHANED: root part missing]"
    print(
        f"trace {tree.get('trace_id')} root={tree.get('root')} "
        f"dur={tree.get('dur_ns', 0) / 1e6:.2f}ms{extra}{flag}",
        file=out,
    )
    name_w = max(len("  " * depth(s) + s["name"]) for s in spans)
    loc_w = max((len(_span_loc(s)) for s in spans), default=0)
    for s in sorted(spans, key=lambda s: (s["start_ns"], s["id"])):
        off_ns = s["start_ns"] - t0
        dur_ns = max(s.get("dur_ns", 0), 0)
        lo = min(int(off_ns * _BAR_WIDTH / root_dur), _BAR_WIDTH - 1)
        hi = min(
            max(int((off_ns + dur_ns) * _BAR_WIDTH / root_dur), lo + 1),
            _BAR_WIDTH,
        )
        bar = " " * lo + "█" * (hi - lo) + " " * (_BAR_WIDTH - hi)
        label = "  " * depth(s) + s["name"]
        loc = f" {_span_loc(s):<{loc_w}}" if loc_w else ""
        badge = f"  ⇐{s['origin']}" if s.get("origin") else ""
        print(
            f"  {off_ns / 1e6:9.3f}ms |{bar}|{loc} "
            f"{dur_ns / 1e6:9.3f}ms  {label:<{name_w}}"
            f"{_fmt_tags(s.get('tags'))}{badge}",
            file=out,
        )


def dump_traces(path: str, out=None) -> None:
    """Render a /v1/debug/traces JSON dump (or one tree per line)."""
    import json

    out = out if out is not None else sys.stdout
    with open(path, "r", encoding="utf-8") as f:
        text = f.read().strip()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = {"ring": [json.loads(ln) for ln in text.splitlines() if ln.strip()]}
    if isinstance(doc, list):
        doc = {"ring": doc}
    frozen = doc.get("frozen", [])
    ring = doc.get("ring", [])
    frozen_ids = {t.get("trace_id") for t in frozen}
    print(
        f"flight recorder node={doc.get('node_id', '?')} "
        f"trees_total={doc.get('trees_total', len(ring))} "
        f"frozen={len(frozen)} "
        f"slow_threshold={doc.get('slow_threshold_ms', '?')}ms",
        file=out,
    )
    for tree in frozen:
        render_tree(tree, out=out, slow=True)
    for tree in ring:
        if tree.get("trace_id") in frozen_ids:
            continue  # already rendered above, flagged slow
        render_tree(tree, out=out)
    # fleet dump (--shards N): per-worker recorder summaries, then the
    # cross-process stitched trees (each span carries shard/node)
    shard_dumps = doc.get("shards") or {}
    for sid in sorted(shard_dumps, key=str):
        sd = shard_dumps[sid]
        print(
            f"shard {sid} (node={sd.get('node_id', '?')}): "
            f"trees_total={sd.get('trees_total', 0)} "
            f"frozen={len(sd.get('frozen', []))} "
            f"ring={len(sd.get('ring', []))}",
            file=out,
        )
    stitched = doc.get("stitched") or []
    if stitched:
        print(f"stitched cross-process traces ({len(stitched)}):", file=out)
        for tree in stitched:
            render_tree(tree, out=out)
    events = doc.get("events", [])
    if events:
        print(f"events ({len(events)}):", file=out)
        for e in events:
            print(
                f"  {e.get('at_ns', 0) / 1e6:.3f}ms {e.get('name')}"
                f"{_fmt_tags(e.get('tags'))}",
                file=out,
            )


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("data_dir", nargs="?")
    ap.add_argument("--ntp", help="ns/topic/partition to dump")
    ap.add_argument(
        "--controller", action="store_true", help="decode the raft0 log"
    )
    ap.add_argument(
        "--traces",
        metavar="FILE",
        help="render a /v1/debug/traces JSON dump as span waterfalls",
    )
    ap.add_argument(
        "--health",
        metavar="FILE",
        help="render a /v1/cluster/partition_health JSON dump "
        "(tools/health_report.py --json output)",
    )
    ap.add_argument(
        "--alerts",
        metavar="FILE",
        help="render a /v1/alerts JSON dump (burn-rate SLO section)",
    )
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    if args.traces:
        dump_traces(args.traces)
        return
    if args.health:
        import json

        from tools.health_report import render_report

        with open(args.health, "r", encoding="utf-8") as f:
            render_report(json.load(f))
        return
    if args.alerts:
        import json

        from tools.health_report import render_alerts

        with open(args.alerts, "r", encoding="utf-8") as f:
            render_alerts(json.load(f))
        return
    if not args.data_dir:
        ap.error(
            "data_dir is required unless --traces, --health or "
            "--alerts is given"
        )

    if args.controller:
        cdir = os.path.join(args.data_dir, "group_0")
        if not os.path.isdir(cdir):
            raise SystemExit(f"no controller log at {cdir}")
        print("controller log (raft group 0):")
        dump_log(cdir, args.verbose, controller=True)
        return

    ntps = find_ntp_dirs(args.data_dir)
    if args.ntp:
        if args.ntp not in ntps:
            raise SystemExit(
                f"unknown ntp {args.ntp}; have: {', '.join(ntps) or 'none'}"
            )
        print(f"{args.ntp}:")
        dump_log(ntps[args.ntp], args.verbose)
        return

    print(f"{args.data_dir}: {len(ntps)} partition logs")
    for ntp, d in ntps.items():
        segs = segments_of(d)
        total = sum(os.path.getsize(os.path.join(d, s)) for s in segs)
        batches = records = 0
        last = None
        for s in segs:
            for _pos, b, _n in iter_batches(os.path.join(d, s)):
                if b is not None:
                    batches += 1
                    records += b.header.record_count
                    last = b.header.last_offset
        print(
            f"  {ntp}: {len(segs)} segments, {total}B, "
            f"{batches} batches, {records} records, last offset {last}"
        )


if __name__ == "__main__":
    main()
