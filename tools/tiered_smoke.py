"""Tiered chaos smoke: one seeded ObjectNemesis run, replay-checked.

verify.sh's cloud leg: boots the 3-broker chaos cluster with a tiered
topic, arms a fixed mixed object-store fault schedule (partial
uploads, torn manifests, slow links, transient errors, throttles) on
top of broker faults, and holds the run to the chaos invariants (no
acked record lost, no manifest pointing at a missing/truncated
object). Then the determinism contract: the firing trace must replay
byte-equal from (rules, seed, recorded op sequence) — a chaos failure
here is a repro command, not an anecdote.

Usage:
    python tools/tiered_smoke.py [--seed N] [--duration S]
"""

import argparse
import asyncio
import os
import sys
import tempfile
from dataclasses import replace
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"
    ),
)


def default_rules():
    from redpanda_tpu.cloud import StoreRule

    return [
        StoreRule(op="put", action="partial", prob=0.15),
        StoreRule(
            op="put", key_glob="*manifest.bin", action="error", prob=0.1
        ),
        StoreRule(
            op="get_range",
            action="slow",
            prob=0.1,
            delay_s=0.0,
            bandwidth_bps=512 * 1024,
        ),
        StoreRule(op="get", action="error", prob=0.1),
        StoreRule(op="*", action="throttle", prob=0.05, delay_s=0.02),
    ]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seed", type=int, default=515)
    ap.add_argument("--duration", type=float, default=3.0)
    args = ap.parse_args()

    from chaos_harness import run_chaos
    from redpanda_tpu.cloud import StoreFaultSchedule
    from redpanda_tpu.cloud.nemesis import replay_trace

    rules = default_rules()
    sched = StoreFaultSchedule(
        rules=[replace(r) for r in rules], seed=args.seed
    )
    shm = "/dev/shm" if os.path.isdir("/dev/shm") else None
    with tempfile.TemporaryDirectory(prefix="tiered_smoke_", dir=shm) as d:
        stats = asyncio.run(
            run_chaos(
                Path(d),
                seed=args.seed,
                duration_s=args.duration,
                faults=("partition", "crash", "transfer"),
                tiered=True,
                store_faults=sched,
            )
        )
    assert stats["acked"] > 0, stats
    assert stats["tiered_archived"] >= 1, stats
    replayed = replay_trace(rules, args.seed, sched.ops)
    assert replayed == sched.trace, (
        f"trace replay diverged: {len(replayed)} vs {len(sched.trace)} "
        f"entries — determinism contract broken (seed {args.seed})"
    )
    print(
        f"tiered smoke ok: seed={args.seed} acked={stats['acked']} "
        f"archived={stats['tiered_archived']} trimmed={stats['tiered_trimmed']} "
        f"store_ops={stats['store_ops']} faults={stats['store_faults']} "
        f"trace={stats['store_trace_len']} (replay-equal)"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
