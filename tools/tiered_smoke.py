"""Tiered chaos smoke: one seeded ObjectNemesis run, replay-checked.

verify.sh's cloud leg: boots the 3-broker chaos cluster with a tiered
topic, arms a fixed mixed object-store fault schedule (partial
uploads, torn manifests, slow links, transient errors, throttles) on
top of broker faults, and holds the run to the chaos invariants (no
acked record lost, no manifest pointing at a missing/truncated
object). Then the determinism contract: the firing trace must replay
byte-equal from (rules, seed, recorded op sequence) — a chaos failure
here is a repro command, not an anecdote.

`--zstd` runs the device-zstd archive leg instead: single broker,
RP_ARCHIVE_COMPRESSION=zstd + RP_ZSTD_BACKEND=tpu, produce ->
archive -> evict -> cold read, asserting the stored objects are zstd
frames (smaller than the logical bytes) and the hydrated records are
byte-identical — plus the stand-down contract for RP_ZSTD_BACKEND=
host (works when the zstandard wheel is installed, refuses loudly
when it is not).

Usage:
    python tools/tiered_smoke.py [--seed N] [--duration S] [--zstd]
"""

import argparse
import asyncio
import os
import sys
import tempfile
from dataclasses import replace
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"
    ),
)


def default_rules():
    from redpanda_tpu.cloud import StoreRule

    return [
        StoreRule(op="put", action="partial", prob=0.15),
        StoreRule(
            op="put", key_glob="*manifest.bin", action="error", prob=0.1
        ),
        StoreRule(
            op="get_range",
            action="slow",
            prob=0.1,
            delay_s=0.0,
            bandwidth_bps=512 * 1024,
        ),
        StoreRule(op="get", action="error", prob=0.1),
        StoreRule(op="*", action="throttle", prob=0.05, delay_s=0.02),
    ]


async def _zstd_leg() -> int:
    from redpanda_tpu import compression
    from redpanda_tpu.app import Broker, BrokerConfig
    from redpanda_tpu.cloud import MemoryObjectStore
    from redpanda_tpu.compression import CompressionType, zstd_frame as zf
    from redpanda_tpu.kafka.client import KafkaClient
    from redpanda_tpu.models.fundamental import kafka_ntp
    from redpanda_tpu.rpc.loopback import LoopbackNetwork

    n_records, record_bytes, batch = 300, 512, 20
    pat = b'{"key":"user-000001","topic":"orders","seq":12345},'
    payload = (pat * (record_bytes // len(pat) + 1))[:record_bytes]

    shm = "/dev/shm" if os.path.isdir("/dev/shm") else None
    with tempfile.TemporaryDirectory(prefix="zstd_smoke_", dir=shm) as tmp:
        store = MemoryObjectStore()
        b = Broker(
            BrokerConfig(
                node_id=0,
                data_dir=os.path.join(tmp, "n0"),
                members=[0],
                enable_admin=False,
                node_status_interval_s=0,
                housekeeping_interval_s=0,
                archival_interval_s=0,
            ),
            loopback=LoopbackNetwork(),
            object_store=store,
        )
        await b.start()
        b.config.peer_kafka_addresses = {0: b.kafka_advertised}
        client = None
        try:
            await b.wait_controller_leader()
            client = KafkaClient([b.kafka_advertised])
            await client.create_topic(
                "zstd-smoke",
                partitions=1,
                replication_factor=1,
                configs={
                    "redpanda.remote.write": "true",
                    "redpanda.remote.read": "true",
                    "segment.bytes": "4096",
                    "retention.local.target.bytes": "4096",
                },
            )
            expect = []
            for base in range(0, n_records, batch):
                recs = [
                    (b"k%06d" % i, payload)
                    for i in range(base, base + batch)
                ]
                await client.produce("zstd-smoke", 0, recs)
                expect.extend(recs)
            p = b.partition_manager.get(kafka_ntp("zstd-smoke", 0))
            p.log.flush()
            await b.archival.run_once()
            b.storage.log_mgr.housekeeping()

            manifest = p.archiver.manifest
            assert manifest.segments, "nothing archived"
            logical = stored = 0
            for m in manifest.segments:
                comp = int(getattr(m, "size_compressed", 0))
                assert comp > 0, "segment archived uncompressed"
                blob = await store.get(manifest.segment_key(m))
                assert len(blob) == comp, (len(blob), comp)
                # stored object is a stock zstd frame declaring the
                # segment's logical size
                assert zf.frame_content_size(blob) == int(m.size_bytes)
                logical += int(m.size_bytes)
                stored += comp
            assert stored < logical, (stored, logical)
            assert int(p.log.offsets().start_offset) > 0, (
                "local prefix never evicted: cold path not exercised"
            )

            # cold read re-hydrates everything through uncompress_zstd
            for m in manifest.segments:
                await b.remote_reader.invalidate(manifest.segment_key(m))
            got = await client.fetch("zstd-smoke", 0, 0, max_bytes=1 << 24)
            assert len(got) == n_records, (len(got), n_records)
            assert [(k, v) for _o, k, v in got] == expect

            # stand-down: the host leg must either work (wheel present)
            # or refuse loudly — never silently fall back to the device
            os.environ["RP_ZSTD_BACKEND"] = "host"
            frame = None
            try:
                frame = compression.compress(payload, CompressionType.zstd)
                standdown = "host leg active (zstandard wheel)"
            except RuntimeError:
                standdown = "host leg refused (wheel absent)"
            if frame is not None:
                assert (
                    compression.uncompress(frame, CompressionType.zstd)
                    == payload
                )
            print(
                f"zstd smoke ok: records={n_records} "
                f"segments={len(manifest.segments)} logical={logical} "
                f"stored={stored} ratio={stored / logical:.3f} "
                f"standdown='{standdown}'"
            )
        finally:
            if client is not None:
                await client.close()
            await b.stop()
    return 0


def run_zstd() -> int:
    save = {
        k: os.environ.get(k)
        for k in ("RP_ARCHIVE_COMPRESSION", "RP_ZSTD_BACKEND")
    }
    os.environ["RP_ARCHIVE_COMPRESSION"] = "zstd"
    os.environ["RP_ZSTD_BACKEND"] = "tpu"
    try:
        return asyncio.run(_zstd_leg())
    finally:
        for k, v in save.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seed", type=int, default=515)
    ap.add_argument("--duration", type=float, default=3.0)
    ap.add_argument(
        "--zstd",
        action="store_true",
        help="device-zstd archive round-trip + stand-down leg",
    )
    args = ap.parse_args()
    if args.zstd:
        return run_zstd()

    from chaos_harness import run_chaos
    from redpanda_tpu.cloud import StoreFaultSchedule
    from redpanda_tpu.cloud.nemesis import replay_trace

    rules = default_rules()
    sched = StoreFaultSchedule(
        rules=[replace(r) for r in rules], seed=args.seed
    )
    shm = "/dev/shm" if os.path.isdir("/dev/shm") else None
    with tempfile.TemporaryDirectory(prefix="tiered_smoke_", dir=shm) as d:
        stats = asyncio.run(
            run_chaos(
                Path(d),
                seed=args.seed,
                duration_s=args.duration,
                faults=("partition", "crash", "transfer"),
                tiered=True,
                store_faults=sched,
            )
        )
    assert stats["acked"] > 0, stats
    assert stats["tiered_archived"] >= 1, stats
    replayed = replay_trace(rules, args.seed, sched.ops)
    assert replayed == sched.trace, (
        f"trace replay diverged: {len(replayed)} vs {len(sched.trace)} "
        f"entries — determinism contract broken (seed {args.seed})"
    )
    print(
        f"tiered smoke ok: seed={args.seed} acked={stats['acked']} "
        f"archived={stats['tiered_archived']} trimmed={stats['tiered_trimmed']} "
        f"store_ops={stats['store_ops']} faults={stats['store_faults']} "
        f"trace={stats['store_trace_len']} (replay-equal)"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
