"""verify.sh placement smoke: boot a 2-shard ShardedBroker, force one
LIVE partition move through the admin endpoint while a producer is
pumping records into that exact partition, then prove the three things
a live move must never break:

  1. zero committed-record loss and zero duplication — every acked
     record is fetchable exactly once after the move;
  2. the placement table rebound (admin /v1/placement shows the new
     shard and the move accounted);
  3. the merged fleet /metrics stays exact — one skew gauge, scrape
     still serves after the partition changed shards.

Exit 0 = live moves work end-to-end in this environment. The full
protocol matrix (per-stage fault rollback, budget, rebalancer) lives
in tests/test_placement.py; this is the "does a real move under real
produce traffic hold the invariants" gate.
"""

import asyncio
import json
import os
import shutil
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_PARTITIONS = 4
TOPIC = "mvsmoke"


def _get(port: int, path: str) -> dict:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return json.loads(r.read().decode())


def _post(port: int, path: str) -> dict:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", method="POST", data=b""
    )
    with urllib.request.urlopen(req, timeout=60) as r:
        return json.loads(r.read().decode())


def _metrics(port: int) -> str:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10
    ) as r:
        return r.read().decode()


async def main() -> None:
    from redpanda_tpu.app import BrokerConfig
    from redpanda_tpu.kafka.client import KafkaClient
    from redpanda_tpu.ssx.sharded_broker import ShardedBroker

    tmp = tempfile.mkdtemp(prefix="placement_smoke_")
    cfg = BrokerConfig(
        node_id=0,
        data_dir=tmp,
        members=[0],
        election_timeout_s=0.3,
        heartbeat_interval_s=0.05,
    )
    sb = ShardedBroker(cfg, n_shards=2)
    await sb.start()
    try:
        assert sb.active, f"unexpected stand-down: {sb.standdown}"
        admin = sb.broker.admin.port
        c = KafkaClient([("127.0.0.1", sb.kafka_port)])
        try:
            deadline = time.monotonic() + 30
            while True:
                try:
                    await c.create_topic(
                        TOPIC, partitions=N_PARTITIONS, replication_factor=1
                    )
                    break
                except Exception:
                    if time.monotonic() > deadline:
                        raise
                    await asyncio.sleep(0.2)
            for p in range(N_PARTITIONS):
                while True:
                    try:
                        await c.produce(TOPIC, p, [(b"seed", b"v%d" % p)])
                        break
                    except Exception:
                        if time.monotonic() > deadline:
                            raise
                        await asyncio.sleep(0.2)

            # pick the mover from the live table
            plc = await asyncio.to_thread(_get, admin, "/v1/placement")
            entry = next(
                e for e in plc["entries"]
                if e["ntp"].startswith(f"kafka/{TOPIC}/")
            )
            ns, topic, pid = entry["ntp"].split("/")
            pid = int(pid)
            src, dst = entry["shard"], 1 - entry["shard"]

            # produce INTO the moving partition while the move runs;
            # keys are unique per attempt, `acked` records what the
            # broker acknowledged — the exactly-once ledger
            acked: list[bytes] = []
            stop = asyncio.Event()

            async def pump() -> None:
                i = 0
                while not stop.is_set():
                    key = b"k%06d" % i
                    i += 1
                    try:
                        await c.produce(TOPIC, pid, [(key, b"v")])
                        acked.append(key)
                    except Exception:
                        # freeze window / leadership handoff: retry
                        # with a FRESH key so an ambiguous outcome can
                        # never double-count
                        await asyncio.sleep(0.05)
                    await asyncio.sleep(0)

            pump_task = asyncio.ensure_future(pump())
            await asyncio.sleep(0.3)
            moved = await asyncio.to_thread(
                _post, admin,
                f"/v1/placement/move/{ns}/{topic}/{pid}?shard={dst}",
            )
            assert moved.get("moved"), moved
            assert moved["from"] == src and moved["to"] == dst, moved
            await asyncio.sleep(0.3)
            stop.set()
            await pump_task
            assert acked, "producer never landed a record"

            # 1. fetch parity: every acked record exactly once, in order
            got: list[bytes] = []
            off = 0
            while True:
                rows = await c.fetch(TOPIC, pid, off)
                if not rows:
                    break
                got.extend(k for _o, k, _v in rows)
                off = rows[-1][0] + 1
            body = [k for k in got if k != b"seed"]
            assert len(body) == len(set(body)), "duplicated records"
            missing = set(acked) - set(body)
            assert not missing, f"lost {len(missing)} acked records"

            # 2. the table rebound and the move is accounted
            plc = await asyncio.to_thread(_get, admin, "/v1/placement")
            entry = next(
                e for e in plc["entries"]
                if e["ntp"] == f"{ns}/{topic}/{pid}"
            )
            assert entry["shard"] == dst, entry
            assert plc["table"]["moves_executed"] >= 1, plc["table"]
            assert plc["mover"]["stats"]["ok"] >= 1, plc["mover"]
            # the alert loop is wired (skew sampling + on_fire hook)
            assert plc["rebalancer"] is not None, plc

            # 3. merged fleet /metrics stays exact post-move
            text = await asyncio.to_thread(_metrics, admin)
            skew_lines = [
                ln for ln in text.splitlines()
                if ln.startswith("redpanda_tpu_placement_shard_skew")
                and not ln.startswith("#")
            ]
            assert len(skew_lines) == 1, skew_lines
        finally:
            await c.close()
    finally:
        await sb.stop()
        shutil.rmtree(tmp, ignore_errors=True)
    print("PLACEMENT-SMOKE-OK")


if __name__ == "__main__":
    asyncio.run(main())
