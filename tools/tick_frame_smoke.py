#!/usr/bin/env python
"""Tick-frame smoke: the batched live replication plane at 100k rows.

Two phases, both deterministic (fixed seeds):

  1. scale smoke (default): build a 100k-row ShardGroupArrays by
     direct lane writes (no Consensus/disk — this gates the MATH and
     the fold plumbing, not group setup), push a randomized reply
     schedule through a real TickFrame, and differentially check a
     row sample against quorum_scalar.leader_commit_index after every
     fold. A gross O(groups)-per-fold interpreter regression also
     trips the generous per-fold wall bound.

  2. --parity: replay the IDENTICAL schedule twice — once under
     RP_QUORUM_BACKEND=host (the numpy fallback) and once under
     =device — and require byte-identical commit_index/last_visible
     lanes plus identical advanced-row sets. The fallback leg of
     tools/verify.sh runs this so a device-only semantic drift cannot
     hide behind the host default.

Exit 0 on success; any assertion failure is a gate failure.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def build(n: int, seed: int):
    """n allocated rows with randomized quorum lanes (vectorized
    writes; every row keeps SELF a current voter)."""
    from redpanda_tpu.models.consensus_state import SELF_SLOT
    from redpanda_tpu.raft.shard_state import NO_OFFSET, ShardGroupArrays

    arrays = ShardGroupArrays(capacity=n)
    rows = np.array([arrays.alloc_row() for _ in range(n)], np.int64)
    rng = np.random.default_rng(seed)
    r = arrays.replica_slots
    match = rng.integers(-1, 400, (n, r)).astype(np.int64)
    flushed = np.maximum(match - rng.integers(0, 40, (n, r)), NO_OFFSET)
    sent = rng.random((n, r)) < 0.15
    match[sent] = NO_OFFSET
    flushed[sent] = NO_OFFSET
    voter = rng.random((n, r)) < 0.6
    voter[:, SELF_SLOT] = True
    old = np.zeros((n, r), bool)
    joint = rng.random(n) < 0.25
    old[joint] = rng.random((int(joint.sum()), r)) < 0.5
    arrays.match_index[rows] = match
    arrays.flushed_index[rows] = flushed
    arrays.is_voter[rows] = voter
    arrays.is_voter_old[rows] = old
    arrays.is_leader[rows] = True
    arrays.commit_index[rows] = rng.integers(-1, 200, n)
    arrays.term_start[rows] = rng.integers(0, 300, n)
    arrays.last_visible[rows] = arrays.commit_index[rows]
    arrays.voter_epoch += 1
    arrays.touch()
    arrays.quorum_dirty[:] = False
    # baseline sweep: bring every row's commit to a lane-consistent
    # state (in the live system group registration marks rows dirty
    # and the first tick sweeps them; direct lane writes bypass that)
    empty = np.empty(0, np.int64)
    arrays.frame_tick(empty, empty, empty, empty, empty, force_rows=rows)
    return arrays, rows


def schedule(n: int, rows: np.ndarray, rounds: int, per_round: int, seed: int):
    """Deterministic reply schedule: per round, `per_round` UNIQUE
    rows each get one reply on a random non-SELF slot; round k carries
    seq k+1 (monotone per lane), with round 3 replaying round 2's seq
    (stale — the guard must drop it identically on both backends)."""
    rng = np.random.default_rng(seed)
    out = []
    for k in range(rounds):
        pick = rng.choice(n, size=min(per_round, n), replace=False)
        rr = rows[pick]
        slots = rng.integers(1, 8, len(rr)).astype(np.int64)
        dirty = rng.integers(-1, 1000, len(rr)).astype(np.int64)
        flushed = np.maximum(dirty - rng.integers(0, 25, len(rr)), -1)
        seq = np.full(len(rr), (2 if k == 3 else k) + 1, np.int64)
        out.append((rr, slots, dirty, flushed, seq.astype(np.int64)))
    return out


def oracle_check(arrays, rows, sample: int, seed: int) -> None:
    """Sampled differential: batched commit decisions vs the scalar
    oracle, same replica construction as scalar_commit_update."""
    from redpanda_tpu.models.consensus_state import SELF_SLOT
    from redpanda_tpu.raft import quorum_scalar as qs

    rng = np.random.default_rng(seed)
    pick = rng.choice(len(rows), size=min(sample, len(rows)), replace=False)
    for row in rows[pick]:
        row = int(row)
        replicas = [
            qs.ReplicaState(
                match_index=int(arrays.match_index[row, s]),
                flushed_index=int(arrays.flushed_index[row, s]),
                is_voter=bool(arrays.is_voter[row, s]),
                is_voter_old=bool(arrays.is_voter_old[row, s]),
            )
            for s in range(arrays.replica_slots)
            if arrays.is_voter[row, s] or arrays.is_voter_old[row, s]
        ]
        want = qs.leader_commit_index(
            replicas,
            leader_flushed=int(arrays.flushed_index[row, SELF_SLOT]),
            commit_index=int(arrays.commit_index[row]),
            term_start=int(arrays.term_start[row]),
        )
        got = int(arrays.commit_index[row])
        assert got == want, (
            f"row {row}: batched commit {got} != scalar oracle {want}"
        )


def run_schedule(n: int, seed: int):
    """One full replay: fresh arrays + TickFrame, fold every round.
    The first two folds are compile warmup; from round 2 the compile
    guard (RP_COMPILEGUARD=1) treats any further jit trace as a
    steady-state recompile finding. Returns (arrays, rows,
    advanced_sets, fold_times)."""
    from redpanda_tpu.raft.tick_frame import TickFrame
    from redpanda_tpu.utils import compileguard

    arrays, rows = build(n, seed)
    frame = TickFrame(arrays)
    sched = schedule(n, rows, rounds=8, per_round=max(1, n // 5), seed=seed)
    advanced_sets = []
    times = []
    compileguard.reset()
    for k, (rr, slots, dirty, flushed, seq) in enumerate(sched):
        if k == 2:
            compileguard.steady()
        t0 = time.perf_counter()
        advanced = frame.fold_now(rr, slots, dirty, flushed, seq)
        times.append(time.perf_counter() - t0)
        advanced_sets.append(np.sort(np.asarray(advanced, np.int64)))
    return arrays, rows, advanced_sets, times


def guard_check() -> str:
    """Fail the smoke on any steady-state recompile report; returns
    the status fragment for the OK line."""
    from redpanda_tpu.utils import compileguard

    if not compileguard.enabled():
        return ""
    reps = compileguard.reports()
    assert not reps, "steady-state recompiles:\n" + "\n".join(
        r.render() for r in reps
    )
    return ", compile-guard clean"


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--groups",
        type=int,
        default=int(os.environ.get("RP_SMOKE_GROUPS", "100000")),
    )
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument(
        "--parity",
        action="store_true",
        help="replay the schedule under RP_QUORUM_BACKEND=host and "
        "=device and require byte-identical commit decisions",
    )
    args = ap.parse_args()
    n = args.groups

    if args.parity:
        lanes = {}
        for backend in ("host", "device"):
            os.environ["RP_QUORUM_BACKEND"] = backend
            arrays, rows, advanced_sets, _ = run_schedule(n, args.seed)
            lanes[backend] = (
                arrays.commit_index[rows].tobytes(),
                arrays.last_visible[rows].tobytes(),
                [a.tobytes() for a in advanced_sets],
            )
        assert lanes["host"][0] == lanes["device"][0], (
            "commit_index diverged host vs device"
        )
        assert lanes["host"][1] == lanes["device"][1], (
            "last_visible diverged host vs device"
        )
        assert lanes["host"][2] == lanes["device"][2], (
            "advanced-row sets diverged host vs device"
        )
        print(
            f"tick-frame parity OK: {n} rows, "
            f"{len(lanes['host'][2])} folds byte-identical host vs "
            f"device{guard_check()}"
        )
        return 0

    arrays, rows, advanced_sets, times = run_schedule(n, args.seed)
    oracle_check(arrays, rows, sample=2000, seed=args.seed + 1)
    worst_ms = max(times) * 1e3
    per_part_ns = (sum(times) / len(times)) / n * 1e9
    n_adv = sum(len(a) for a in advanced_sets)
    print(
        f"tick-frame smoke OK: {n} rows, {len(times)} folds, "
        f"{n_adv} advances, worst fold {worst_ms:.1f} ms, "
        f"{per_part_ns:.0f} ns/partition/fold, 2000-row oracle sample "
        f"clean{guard_check()}"
    )
    # generous interpreter-regression bound: a per-group Python loop
    # at 100k rows costs seconds per fold, vectorized folds cost ~ms
    budget_ms = 2000.0
    assert worst_ms < budget_ms, (
        f"fold took {worst_ms:.0f} ms at {n} rows — per-group "
        "interpreter work crept back into the tick frame"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
