"""verify.sh mp smoke: boot a 2-shard ShardedBroker (real forked
worker, SO_REUSEPORT listener), run one produce/fetch round across a
partition spread that crosses the invoke_on seam, check the work
actually landed on the worker shard, then exercise the elastic
lifecycle: grow a third shard, produce through it, SIGKILL a grow
mid-handshake (rollback, zero orphans), retire the grown shard, and
shut down cleanly.

Exit 0 = the shard runtime forks, serves, and stands down on this
machine. Kept deliberately small (~seconds) — the full matrix lives in
tests/test_shards.py; this is the "does the fork path work at all in
this environment" gate.
"""

import asyncio
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_PARTITIONS = 4


async def main() -> None:
    from redpanda_tpu.app import BrokerConfig
    from redpanda_tpu.kafka.client import KafkaClient
    from redpanda_tpu.ssx.sharded_broker import ShardedBroker

    tmp = tempfile.mkdtemp(prefix="shard_smoke_")
    cfg = BrokerConfig(
        node_id=0,
        data_dir=tmp,
        members=[0],
        election_timeout_s=0.3,
        heartbeat_interval_s=0.05,
        enable_admin=False,
    )
    sb = ShardedBroker(cfg, n_shards=2)
    await sb.start()
    try:
        assert sb.active, f"unexpected stand-down: {sb.standdown}"
        c = KafkaClient([("127.0.0.1", sb.kafka_port)])
        try:
            deadline = time.monotonic() + 30
            while True:
                try:
                    await c.create_topic(
                        "smoke", partitions=N_PARTITIONS, replication_factor=1
                    )
                    break
                except Exception:
                    if time.monotonic() > deadline:
                        raise
                    await asyncio.sleep(0.2)
            for p in range(N_PARTITIONS):
                while True:
                    try:
                        await c.produce("smoke", p, [(b"k", b"v%d" % p)])
                        break
                    except Exception:
                        if time.monotonic() > deadline:
                            raise
                        await asyncio.sleep(0.2)
            for p in range(N_PARTITIONS):
                rows = await c.fetch("smoke", p, 0)
                assert len(rows) == 1, (p, rows)
            stats = await sb.shard_stats()
            assert stats and stats[0].partitions > 0, (
                f"no partitions on the worker shard: {stats}"
            )
            assert stats[0].produce_reqs > 0, (
                "no produce crossed the invoke_on seam"
            )

            # -- elastic lifecycle legs ------------------------------
            from redpanda_tpu.ssx import ProcRule, ProcSchedule

            lc = sb.lifecycle
            rt = sb.runtime
            # grow: fork shard 2, mesh + activate, then produce through
            # the grown topology
            sid = await lc.grow()
            assert sid == 2 and sid in rt.shard_pids, (sid, rt.shard_pids)
            assert sb.broker.shard_table.is_available(sid)
            for p in range(N_PARTITIONS):
                await c.produce("smoke", p, [(b"k", b"grown%d" % p)])
            # SIGKILL mid-grow (injected at the grow.ready boundary):
            # the provisional shard 3 must roll back — no orphan pid,
            # no table residue
            rt.nemesis = ProcSchedule(
                rules=[ProcRule(event="grow.ready", action="kill")], seed=1
            )
            before = set(rt.shard_pids)
            try:
                await lc.grow()
                raise AssertionError("killed grow reported success")
            except AssertionError:
                raise
            except Exception:
                pass  # rollback path
            rt.nemesis = None
            assert set(rt.shard_pids) == before, (
                f"orphan after aborted grow: {rt.shard_pids} vs {before}"
            )
            assert 3 not in sb.broker.shard_table.active_shards()
            # retire shard 2: freeze -> evacuate -> drain -> reap, then
            # the evacuated groups still serve
            pid2 = rt.shard_pids[sid]
            await lc.retire(sid)
            assert sid not in rt.shard_pids
            try:
                os.kill(pid2, 0)
                raise AssertionError(f"retired shard pid {pid2} survives")
            except ProcessLookupError:
                pass
            for p in range(N_PARTITIONS):
                rows = await c.fetch("smoke", p, 0)
                assert rows, f"partition {p} lost after retire"
                await c.produce("smoke", p, [(b"k", b"post%d" % p)])
            desc = lc.describe()
            assert desc["grows"] >= 1 and desc["retires"] >= 1, desc
            assert desc["rolled_back"] >= 1, desc
        finally:
            await c.close()
    finally:
        await sb.stop()
        shutil.rmtree(tmp, ignore_errors=True)
    print("SHARD-SMOKE-OK")


if __name__ == "__main__":
    asyncio.run(main())
