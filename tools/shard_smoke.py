"""verify.sh mp smoke: boot a 2-shard ShardedBroker (real forked
worker, SO_REUSEPORT listener), run one produce/fetch round across a
partition spread that crosses the invoke_on seam, check the work
actually landed on the worker shard, and shut down cleanly.

Exit 0 = the shard runtime forks, serves, and stands down on this
machine. Kept deliberately small (~seconds) — the full matrix lives in
tests/test_shards.py; this is the "does the fork path work at all in
this environment" gate.
"""

import asyncio
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_PARTITIONS = 4


async def main() -> None:
    from redpanda_tpu.app import BrokerConfig
    from redpanda_tpu.kafka.client import KafkaClient
    from redpanda_tpu.ssx.sharded_broker import ShardedBroker

    tmp = tempfile.mkdtemp(prefix="shard_smoke_")
    cfg = BrokerConfig(
        node_id=0,
        data_dir=tmp,
        members=[0],
        election_timeout_s=0.3,
        heartbeat_interval_s=0.05,
        enable_admin=False,
    )
    sb = ShardedBroker(cfg, n_shards=2)
    await sb.start()
    try:
        assert sb.active, f"unexpected stand-down: {sb.standdown}"
        c = KafkaClient([("127.0.0.1", sb.kafka_port)])
        try:
            deadline = time.monotonic() + 30
            while True:
                try:
                    await c.create_topic(
                        "smoke", partitions=N_PARTITIONS, replication_factor=1
                    )
                    break
                except Exception:
                    if time.monotonic() > deadline:
                        raise
                    await asyncio.sleep(0.2)
            for p in range(N_PARTITIONS):
                while True:
                    try:
                        await c.produce("smoke", p, [(b"k", b"v%d" % p)])
                        break
                    except Exception:
                        if time.monotonic() > deadline:
                            raise
                        await asyncio.sleep(0.2)
            for p in range(N_PARTITIONS):
                rows = await c.fetch("smoke", p, 0)
                assert len(rows) == 1, (p, rows)
            stats = await sb.shard_stats()
            assert stats and stats[0].partitions > 0, (
                f"no partitions on the worker shard: {stats}"
            )
            assert stats[0].produce_reqs > 0, (
                "no produce crossed the invoke_on seam"
            )
        finally:
            await c.close()
    finally:
        await sb.stop()
        shutil.rmtree(tmp, ignore_errors=True)
    print("SHARD-SMOKE-OK")


if __name__ == "__main__":
    asyncio.run(main())
