#!/usr/bin/env bash
# Full local verification: static analysis first (fails in seconds on
# a broken invariant, before 10+ minutes of tests), then the native
# library build, then the tier-1 suite with the same flags the driver
# uses — twice-lite: the full suite with the native hot paths live,
# plus a pure-Python smoke pass (RP_NATIVE=0) over the suites that
# gate the native/fallback seam, so a fallback regression can't hide
# behind a working .so.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== rplint (baseline gate) =="
python -m tools.rplint --baseline redpanda_tpu

echo "== rplint race rules (RPL015/016 whole-program, empty by construction) =="
python -m tools.rplint --rules RPL015,RPL016 redpanda_tpu tools tests

echo "== rplint compile discipline (RPL020/021 device plane, empty by construction) =="
python -m tools.rplint --rules RPL020,RPL021 redpanda_tpu

echo "== rplint transfer discipline (RPL018 whole-program incl. tests, empty by construction) =="
python -m tools.rplint --rules RPL018 redpanda_tpu tools tests

echo "== rplint fetch discipline (RPL023 span walk, empty by construction) =="
python -m tools.rplint --rules RPL023 redpanda_tpu tools

echo "== native build =="
if make -s -C native; then
    echo "built native/build/libredpanda_native.so"
else
    echo "WARN: native build failed; suite runs on pure-Python fallbacks"
fi

echo "== observability scrape smoke =="
env JAX_PLATFORMS=cpu python tools/scrape_smoke.py

echo "== tier-1 tests (native) =="
env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly "$@"

echo "== fallback smoke (RP_NATIVE=0) =="
env JAX_PLATFORMS=cpu RP_NATIVE=0 python -m pytest \
    tests/test_native_append.py tests/test_native_records.py \
    tests/test_produce_fast.py tests/test_foundation.py \
    -q -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly

echo "== shard mp smoke (fork + invoke_on seam, grow -> kill-mid-grow rollback -> retire) =="
env JAX_PLATFORMS=cpu python tools/shard_smoke.py

echo "== proc-fault soak smoke (seeded ProcNemesis, 3 iterations) =="
env JAX_PLATFORMS=cpu python tools/chaos_soak.py --proc-faults \
    --iterations 3 --duration 2

echo "== placement smoke (live move mid-produce, fetch parity, merged /metrics) =="
env JAX_PLATFORMS=cpu python tools/placement_smoke.py

echo "== fleet scrape smoke (merged /metrics + stitched traces) =="
env JAX_PLATFORMS=cpu python tools/scrape_smoke.py --fleet

echo "== sharding-off smoke (RP_SHARDS=0) =="
env JAX_PLATFORMS=cpu RP_SHARDS=0 python -m pytest \
    tests/test_kafka_e2e.py \
    -q -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly

echo "== tick-frame smoke (100k-partition live replication plane) =="
env JAX_PLATFORMS=cpu python tools/tick_frame_smoke.py

echo "== tick-frame backend parity (host fallback vs device) =="
env JAX_PLATFORMS=cpu python tools/tick_frame_smoke.py --parity --groups 4096

echo "== compile-guard smoke (RP_COMPILEGUARD=1 device plane, 0 recompiles) =="
env JAX_PLATFORMS=cpu RP_COMPILEGUARD=1 RP_QUORUM_BACKEND=device \
    python tools/tick_frame_smoke.py --groups 4096

echo "== tiered chaos smoke (ObjectNemesis schedule, replay-equal) =="
env JAX_PLATFORMS=cpu python tools/tiered_smoke.py

echo "== race sanitizer smoke (RP_SAN=1 election + produce, 0 reports) =="
env JAX_PLATFORMS=cpu python tools/rpsan_smoke.py

echo "== health-plane smoke (partition_health + bounded /metrics) =="
env JAX_PLATFORMS=cpu python tools/scrape_smoke.py --health

echo "== bench gate selftest (trajectory extraction + grading) =="
python tools/bench_gate.py --selftest

echo "== flight-data smoke (history ring + alerts + profiler) =="
env JAX_PLATFORMS=cpu python tools/scrape_smoke.py --alerts

echo "== flight-data stand-down smoke (RP_ALERTS=0 RP_PROFILE=0) =="
env JAX_PLATFORMS=cpu RP_ALERTS=0 RP_PROFILE=0 \
    python tools/scrape_smoke.py --alerts

echo "== mesh backend smoke (8 forced devices, live parity vs host) =="
env JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    RP_QUORUM_BACKEND=mesh python tools/mesh_smoke.py

echo "== mesh compile-guard smoke (RP_COMPILEGUARD=1, 8 devices, 0 recompiles) =="
env JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    RP_QUORUM_BACKEND=mesh RP_COMPILEGUARD=1 python tools/mesh_smoke.py

echo "== mesh stand-down smoke (RP_QUORUM_BACKEND=host) =="
env JAX_PLATFORMS=cpu RP_QUORUM_BACKEND=host python tools/mesh_smoke.py

echo "== device-plane smoke (RP_DEVPLANE=1, folds==frames + kernel histograms) =="
env JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    RP_DEVPLANE=1 python tools/scrape_smoke.py --devplane

echo "== device-plane stand-down smoke (RP_DEVPLANE unset, instrument is identity) =="
env JAX_PLATFORMS=cpu python tools/scrape_smoke.py --devplane

echo "== device-zstd archive smoke (upload + cold-read parity + stand-down) =="
env JAX_PLATFORMS=cpu python tools/tiered_smoke.py --zstd

echo "== front-end churn smoke (1k clients, RST storms, zero leaks) =="
env JAX_PLATFORMS=cpu python tools/traffic_smoke.py

echo "== front-end fallback smoke (RP_NATIVE_FRAME=0 pure-Python framing) =="
env JAX_PLATFORMS=cpu RP_NATIVE_FRAME=0 python tools/traffic_smoke.py \
    --clients 200 --rounds 2

echo "== consume smoke (2-broker wire plane: parity + verify-on-read + counters) =="
env JAX_PLATFORMS=cpu python tools/consume_smoke.py

echo "== consume stand-down smoke (RP_FETCH_WIRE=0 decoded framing) =="
env JAX_PLATFORMS=cpu RP_FETCH_WIRE=0 python tools/consume_smoke.py

echo "== tracing-off smoke (RP_TRACE=0) =="
env JAX_PLATFORMS=cpu RP_TRACE=0 python tools/scrape_smoke.py --fleet
exec env JAX_PLATFORMS=cpu RP_TRACE=0 python -m pytest \
    tests/test_observability.py tests/test_kafka_e2e.py \
    tests/test_admin_server.py \
    -q -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly
