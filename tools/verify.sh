#!/usr/bin/env bash
# Full local verification: static analysis first (fails in seconds on
# a broken invariant, before 10+ minutes of tests), then the tier-1
# suite with the same flags the driver uses.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== rplint (baseline gate) =="
python -m tools.rplint --baseline redpanda_tpu

echo "== tier-1 tests =="
exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly "$@"
