"""verify.sh mesh smoke: the mesh replication backend on a LIVE
2-broker cluster, not just lane replays.

Two legs, selected by RP_QUORUM_BACKEND (the verify.sh legs set it):

  * mesh leg (RP_QUORUM_BACKEND=mesh, 8 forced host devices): boot two
    brokers over loopback RPC, produce acks=-1 into every partition
    with RP_MESH_FULL=1 so every fold runs the REAL NamedSharding
    program, and assert (a) the mesh is actually live (chip_count > 1,
    per-chip lane attribution sums to the active groups, the one
    cross-chip totals fold ran), then (b) replay the identical
    scenario under RP_QUORUM_BACKEND=host and require byte-identical
    fetch ledgers and end offsets — the live-cluster analog of the
    tick_frame_smoke --parity lane replay.

  * stand-down leg (RP_QUORUM_BACKEND=host): same live scenario, then
    assert the mesh machinery stayed COLD — chip_count() == 1 and the
    MeshFrame was never constructed — so the default path cannot
    silently pay mesh placement costs.

Exit 0 = the selected backend serves real replicated traffic with the
same committed bytes the host oracle produces.
"""

import asyncio
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# must precede any jax import (the brokers import it lazily); verify.sh
# passes these too, but the tool has to be runnable standalone
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

TOPIC = "meshsmoke"
N_PARTITIONS = 4
RECORDS_PER_PARTITION = 24


async def run_scenario(backend: str, mesh_full: bool) -> dict:
    """One full live run under `backend`: 2 brokers, rf=1 topic,
    produce + fetch everything back. Returns the user-visible ledger
    (bytes per partition) plus the broker-side mesh observations."""
    from redpanda_tpu.app import Broker, BrokerConfig
    from redpanda_tpu.kafka.client import KafkaClient
    from redpanda_tpu.rpc.loopback import LoopbackNetwork

    os.environ["RP_QUORUM_BACKEND"] = backend
    if mesh_full:
        os.environ["RP_MESH_FULL"] = "1"
    else:
        os.environ.pop("RP_MESH_FULL", None)

    tmp = tempfile.mkdtemp(prefix=f"mesh_smoke_{backend}_")
    net = LoopbackNetwork()
    members = [0, 1]
    brokers = [
        Broker(
            BrokerConfig(
                node_id=i,
                data_dir=os.path.join(tmp, f"node{i}"),
                members=members,
                election_timeout_s=0.15,
                heartbeat_interval_s=0.03,
            ),
            loopback=net,
        )
        for i in members
    ]
    try:
        for b in brokers:
            await b.start()
        addrs = {b.node_id: b.kafka_advertised for b in brokers}
        for b in brokers:
            b.config.peer_kafka_addresses = addrs
        await brokers[0].wait_controller_leader()

        c = KafkaClient([b.kafka_advertised for b in brokers])
        try:
            deadline = time.monotonic() + 30
            while True:
                try:
                    # rf must be odd; with 2 brokers the partitions
                    # spread across both nodes at rf=1, which is the
                    # point: both brokers' tick frames serve traffic
                    await c.create_topic(
                        TOPIC,
                        partitions=N_PARTITIONS,
                        replication_factor=1,
                    )
                    break
                except Exception:
                    if time.monotonic() > deadline:
                        raise
                    await asyncio.sleep(0.2)

            # compile discipline: the first two partitions' produce
            # traffic is warmup (first folds compile the tick/mesh
            # programs); from there every jit trace is a steady-state
            # recompile finding under RP_COMPILEGUARD=1
            from redpanda_tpu.utils import compileguard

            compileguard.reset()
            for p in range(N_PARTITIONS):
                if p == 2:
                    compileguard.steady()
                for i in range(0, RECORDS_PER_PARTITION, 8):
                    batch = [
                        (b"k%06d" % (i + j), b"v%d.%d" % (p, i + j))
                        for j in range(8)
                    ]
                    while True:
                        try:
                            await c.produce(TOPIC, p, batch, acks=-1)
                            break
                        except Exception:
                            if time.monotonic() > deadline:
                                raise
                            await asyncio.sleep(0.2)

            ledger: dict[int, bytes] = {}
            ends: dict[int, int] = {}
            for p in range(N_PARTITIONS):
                rows = []
                off = 0
                while True:
                    got = await c.fetch(TOPIC, p, off)
                    if not got:
                        break
                    rows.extend(got)
                    off = rows[-1][0] + 1
                assert len(rows) == RECORDS_PER_PARTITION, (
                    f"{backend}: partition {p} fetched {len(rows)} rows, "
                    f"expected {RECORDS_PER_PARTITION}"
                )
                ledger[p] = b"|".join(
                    b"%d:%s:%s" % (o, k, v) for o, k, v in rows
                )
                ends[p] = await c.list_offset(TOPIC, p, -1)
        finally:
            await c.close()

        mesh = []
        for b in brokers:
            arrays = b.group_manager.arrays
            mesh.append(
                {
                    "node": b.node_id,
                    "chips": arrays.chip_count(),
                    "attribution": arrays.lane_attribution(),
                    "totals": arrays.mesh_totals(),
                    "mesh_cold": arrays._mesh_frame is None,
                    "active_groups": int(arrays.row_active.sum()),
                }
            )
        return {"ledger": ledger, "ends": ends, "mesh": mesh}
    finally:
        for b in brokers:
            await b.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def _guard_check() -> str:
    """Fail the smoke on any steady-state recompile; returns the OK
    line's status fragment."""
    from redpanda_tpu.utils import compileguard

    if not compileguard.enabled():
        return ""
    reps = compileguard.reports()
    assert not reps, "steady-state recompiles:\n" + "\n".join(
        r.render() for r in reps
    )
    return ", compile-guard clean"


async def main() -> None:
    backend = os.environ.get("RP_QUORUM_BACKEND", "host")

    if backend == "mesh":
        got = await run_scenario("mesh", mesh_full=True)
        for m in got["mesh"]:
            assert m["chips"] > 1, (
                f"node {m['node']}: mesh backend selected but "
                f"chip_count() == {m['chips']} — forced devices not live"
            )
            per_chip = sum(a["groups"] for a in m["attribution"])
            assert per_chip == m["active_groups"], (
                f"node {m['node']}: per-chip lane attribution "
                f"({per_chip}) != active groups ({m['active_groups']})"
            )
            assert m["active_groups"] > 0, f"node {m['node']}: no groups"
            # acks=-1 produce drove folds through the forced full mesh
            # frame: the one cross-chip totals fold must have run
            assert m["totals"] is not None, (
                f"node {m['node']}: no mesh totals — the full mesh "
                "frame never ran despite RP_MESH_FULL=1"
            )

        # parity replay: identical scenario, host oracle backend
        want = await run_scenario("host", mesh_full=False)
        assert got["ledger"] == want["ledger"], (
            "fetch ledger diverged mesh vs host: "
            + ", ".join(
                f"p{p}" for p in got["ledger"]
                if got["ledger"][p] != want["ledger"].get(p)
            )
        )
        assert got["ends"] == want["ends"], (
            f"end offsets diverged mesh vs host: "
            f"{got['ends']} != {want['ends']}"
        )
        chips = got["mesh"][0]["chips"]
        print(
            f"MESH-SMOKE-OK: mesh backend ({chips} chips), "
            f"{N_PARTITIONS}x{RECORDS_PER_PARTITION} records rf=1, "
            "fetch ledger + end offsets byte-identical vs host"
            + _guard_check()
        )
        return

    got = await run_scenario(backend, mesh_full=False)
    for m in got["mesh"]:
        assert m["chips"] == 1, (
            f"node {m['node']}: chip_count() == {m['chips']} under "
            f"RP_QUORUM_BACKEND={backend} — stand-down leaked mesh"
        )
        assert m["mesh_cold"], (
            f"node {m['node']}: MeshFrame was constructed under "
            f"RP_QUORUM_BACKEND={backend} — the default path must "
            "never touch mesh placement"
        )
    print(
        f"MESH-SMOKE-OK: {backend} stand-down, "
        f"{N_PARTITIONS}x{RECORDS_PER_PARTITION} records rf=1, "
        "mesh machinery cold" + _guard_check()
    )


if __name__ == "__main__":
    asyncio.run(main())
