"""verify.sh race-sanitizer smoke: boot a 3-broker cluster with the
runtime async race sanitizer armed (RP_SAN=1), drive one raft
election plus a produce round on every partition, shut down, and
fail if rpsan recorded a single torn-write report.

Exit 0 = the instrumented hot paths (Consensus role/vote transitions,
HeartbeatManager plan cache, GroupManager sweeper state, flush
coalescer handoff) completed an election + replication round with no
coroutine carrying a stale read across a suspension point. The
seeded positive case (a race that MUST report) lives in
tests/test_rpsan.py; this gate is the negative: production code under
the sanitizer is clean.
"""

import asyncio
import os
import sys
import tempfile
import time
from pathlib import Path

os.environ["RP_SAN"] = "1"  # must precede any redpanda_tpu import
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"
    ),
)

N_PARTITIONS = 3


async def main() -> int:
    from chaos_harness import ChaosCluster
    from redpanda_tpu.kafka.client import KafkaClient
    from redpanda_tpu.utils import rpsan

    assert rpsan.enabled(), "RP_SAN=1 did not arm the sanitizer"
    assert rpsan.INSTRUMENTED, "no classes instrumented under RP_SAN=1"

    with tempfile.TemporaryDirectory(prefix="rpsan_smoke_") as d:
        cluster = ChaosCluster(Path(d), n=3)
        await cluster.start()  # includes waiting out a controller election
        try:
            client = KafkaClient(cluster.addresses())
            try:
                deadline = time.monotonic() + 30
                while True:
                    try:
                        await client.create_topic(
                            "sanity",
                            partitions=N_PARTITIONS,
                            replication_factor=3,
                        )
                        break
                    except Exception:
                        if time.monotonic() > deadline:
                            raise
                        await asyncio.sleep(0.2)
                for p in range(N_PARTITIONS):
                    while True:
                        try:
                            off = await asyncio.wait_for(
                                client.produce(
                                    "sanity",
                                    p,
                                    [(b"k%d" % p, b"v%d" % p)],
                                    acks=-1,
                                ),
                                timeout=5.0,
                            )
                            assert off >= 0
                            break
                        except asyncio.TimeoutError:
                            if time.monotonic() > deadline:
                                raise
            finally:
                await client.close()
        finally:
            await cluster.stop()

    reps = rpsan.reports()
    classes = ", ".join(sorted(c for c, _ in rpsan.INSTRUMENTED))
    if reps:
        print(f"rpsan smoke: {len(reps)} torn-write report(s):")
        for r in reps:
            print("  " + r.render())
        return 1
    print(
        f"rpsan smoke OK: election + {N_PARTITIONS}-partition produce "
        f"round, 0 reports ({classes} instrumented)"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(asyncio.run(main()))
