#!/usr/bin/env python
"""Measure the host-vs-device crossover for the quorum sweep.

VERDICT r2 weak #5: ShardGroupArrays.DEVICE_THRESHOLD_ROWS (16384) was
asserted, not measured. This tool measures a FULL FOLD (every group
advancing — the worst case; steady-state ticks skip the sweep entirely
since the r3 incremental change) through shard_state.host_tick and
through the device path, at several shard sizes, using the honest
device methodology (distinct settled inputs, per-call blocking; see
bench.py bench_fused's note on tunnel artifacts).

Usage:
    python tools/measure_quorum_crossover.py            # axon TPU
    JAX_PLATFORMS=cpu python tools/measure_quorum_crossover.py

Prints a table plus the measured crossover; pass --update-docs to
append the result to the report file under bench_profiles/.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def make_arrays(g: int, backend: str):
    from redpanda_tpu.raft.shard_state import ShardGroupArrays

    a = ShardGroupArrays(capacity=g, replica_slots=8)
    rows = [a.alloc_row() for _ in range(g)]
    a.is_leader[:] = True
    a.is_voter[:, :3] = True
    a.term_start[:] = 0
    a.match_index[:, 0] = 0
    a.flushed_index[:, 0] = 0
    os.environ["RP_QUORUM_BACKEND"] = backend
    return a, np.array(rows, np.int64)


def one_tick(a, rows, offset: int):
    m = len(rows) * 2
    g_rows = np.repeat(rows, 2)
    slots = np.tile(np.array([1, 2], np.int64), len(rows))
    dirty = np.full(m, offset, np.int64)
    seqs = np.full(m, offset + 1, np.int64)
    # leader log advances too, so every group's commit moves (full fold)
    a.match_index[rows, 0] = offset
    a.flushed_index[rows, 0] = offset
    return a.device_tick(g_rows, slots, dirty, dirty, seqs)


def measure(g: int, backend: str, iters: int = 8) -> float:
    a, rows = make_arrays(g, backend)
    one_tick(a, rows, 0)  # warm/compile
    times = []
    for i in range(1, iters + 1):
        t0 = time.perf_counter()
        advanced = one_tick(a, rows, i)
        times.append(time.perf_counter() - t0)
        assert len(advanced) == g, (backend, g, len(advanced))
    os.environ.pop("RP_QUORUM_BACKEND", None)
    return min(times) * 1e3


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--update-docs", action="store_true")
    args = ap.parse_args()
    sizes = [1024, 4096, 16384, 65536, 131072]
    lines = [
        "# quorum sweep host-vs-device crossover "
        "(full fold, every group advancing; ms per tick, min of 8)",
        f"# platform: {os.environ.get('JAX_PLATFORMS', 'axon-tpu')}",
        f"{'groups':>8} {'host_ms':>9} {'device_ms':>10} {'winner':>7}",
    ]
    crossover = None
    for g in sizes:
        host = measure(g, "host")
        dev = measure(g, "device")
        winner = "device" if dev < host else "host"
        if winner == "device" and crossover is None:
            crossover = g
        lines.append(f"{g:>8} {host:>9.3f} {dev:>10.3f} {winner:>7}")
    lines.append(
        f"# measured crossover: device wins from ~{crossover} groups"
        if crossover
        else "# measured crossover: host wins at every tested size "
        "(transfer-bound on this link; DEVICE_THRESHOLD_ROWS stays a "
        "resident-chip setting)"
    )
    report = "\n".join(lines)
    print(report)
    if args.update_docs:
        path = os.path.join(
            os.path.dirname(__file__), "..", "bench_profiles",
            "quorum_crossover.txt",
        )
        with open(path, "w") as f:
            f.write(report + "\n")
        print(f"\nwrote {path}")


if __name__ == "__main__":
    main()
