"""verify.sh consume smoke: boot a live 2-broker cluster, produce a
known ledger, then prove the zero-copy fetch plane end-to-end:

  1. wire/decoded parity — the raw records buffer served by the
     default wire plane is BYTE-IDENTICAL to the one the decoded
     stand-down (`RP_FETCH_WIRE=0`) builds via
     RecordBatch.deserialize + to_kafka_wire, for every partition,
     and the decoded ledger (offset, key, value) matches what was
     produced, in order, exactly once;
  2. verify-on-read — a full replay with `RP_FETCH_VERIFY=1` serves
     the same bytes (the batched device CRC pass flags nothing on
     clean data) and accounts at least one crc verify dispatch;
  3. read-path observability — /metrics exposes the `storage_read`
     counter family, and a repeat fetch on the wire plane lands
     wire-cache hits.

Runs twice from verify.sh: native (wire plane on) and under
`RP_FETCH_WIRE=0`, where leg 1 degenerates to decoded-vs-decoded —
the stand-down must still serve the ledger byte-for-byte.

Exit 0 = the fetch plane holds the ledger on a real cluster. The
randomized differential fuzz (10k+ fetches, truncation/compaction/
eviction interleavings) lives in tests/test_fetch_wire.py; this is
the "does a live cluster serve identical bytes either way" gate.
"""

import asyncio
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TOPIC = "csmoke"
N_PARTITIONS = 2
N_BATCHES = 40
RECORDS_PER_BATCH = 4


def _metrics(port: int) -> str:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10
    ) as r:
        return r.read().decode()


async def _drain_raw(client, pid: int) -> bytes:
    """All records wire bytes for one partition, concatenated across
    fetch rounds from offset 0."""
    out = bytearray()
    pos = 0
    while True:
        wire, nxt = await client.fetch_raw(
            TOPIC, pid, pos, max_bytes=8 << 20
        )
        if not wire or nxt <= pos:
            return bytes(out)
        out += wire
        pos = nxt


async def _drain_ledger(client, pid: int) -> list[tuple[int, bytes, bytes]]:
    got: list[tuple[int, bytes, bytes]] = []
    pos = 0
    while True:
        rows = await client.fetch(TOPIC, pid, pos)
        if not rows:
            return got
        got.extend(rows)
        pos = rows[-1][0] + 1


async def main() -> None:
    from redpanda_tpu.app import Broker, BrokerConfig
    from redpanda_tpu.kafka.client import KafkaClient
    from redpanda_tpu.kafka.server import fetch_wire_enabled
    from redpanda_tpu.rpc.loopback import LoopbackNetwork

    import shutil
    import tempfile

    tmp = tempfile.mkdtemp(prefix="consume_smoke_")
    net = LoopbackNetwork()
    brokers = [
        Broker(
            BrokerConfig(
                node_id=i,
                data_dir=os.path.join(tmp, f"n{i}"),
                members=[0, 1],
                election_timeout_s=0.15,
                heartbeat_interval_s=0.03,
            ),
            loopback=net,
        )
        for i in range(2)
    ]
    for b in brokers:
        await b.start()
    addrs = {b.node_id: b.kafka_advertised for b in brokers}
    for b in brokers:
        b.config.peer_kafka_addresses = addrs
    await brokers[0].wait_controller_leader()
    client = KafkaClient([b.kafka_advertised for b in brokers])
    try:
        import time

        deadline = time.monotonic() + 30
        while True:
            try:
                await client.create_topic(
                    TOPIC, partitions=N_PARTITIONS, replication_factor=1
                )
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise
                await asyncio.sleep(0.2)
        produced: dict[int, list[tuple[bytes, bytes]]] = {
            p: [] for p in range(N_PARTITIONS)
        }
        for pid in range(N_PARTITIONS):
            for i in range(N_BATCHES):
                recs = [
                    (b"k%d-%d-%d" % (pid, i, j), b"v" * (64 + (i * 7 + j) % 200))
                    for j in range(RECORDS_PER_BATCH)
                ]
                await client.produce(TOPIC, pid, recs, acks=-1)
                produced[pid].extend(recs)

        # 1. wire/decoded parity: byte-identical raw buffers + exact ledger
        mode = "wire" if fetch_wire_enabled() else "decoded(stand-down)"
        plane_raw = {
            p: await _drain_raw(client, p) for p in range(N_PARTITIONS)
        }
        prev = os.environ.get("RP_FETCH_WIRE")
        os.environ["RP_FETCH_WIRE"] = "0"
        try:
            decoded_raw = {
                p: await _drain_raw(client, p) for p in range(N_PARTITIONS)
            }
        finally:
            if prev is None:
                os.environ.pop("RP_FETCH_WIRE", None)
            else:
                os.environ["RP_FETCH_WIRE"] = prev
        for pid in range(N_PARTITIONS):
            assert plane_raw[pid], f"p{pid}: empty fetch"
            assert plane_raw[pid] == decoded_raw[pid], (
                f"p{pid}: {mode} plane diverges from decoded framing "
                f"({len(plane_raw[pid])} vs {len(decoded_raw[pid])} bytes)"
            )
            ledger = await _drain_ledger(client, pid)
            assert [(k, v) for _o, k, v in ledger] == produced[pid], (
                f"p{pid}: ledger mismatch ({len(ledger)} rows vs "
                f"{len(produced[pid])} produced)"
            )

        # 2. verify-on-read replay: clean data passes the device CRC
        # gate and serves the same bytes
        prev_v = os.environ.get("RP_FETCH_VERIFY")
        os.environ["RP_FETCH_VERIFY"] = "1"
        try:
            for pid in range(N_PARTITIONS):
                verified = await _drain_raw(client, pid)
                assert verified == plane_raw[pid], (
                    f"p{pid}: RP_FETCH_VERIFY=1 altered served bytes"
                )
        finally:
            if prev_v is None:
                os.environ.pop("RP_FETCH_VERIFY", None)
            else:
                os.environ["RP_FETCH_VERIFY"] = prev_v

        # 3. read-path counters on /metrics; the replay above must have
        # landed wire-cache hits when the wire plane is on (summed over
        # both brokers — leadership places the serving log on either)
        read_lines: list[str] = []
        for b in brokers:
            text = await asyncio.to_thread(_metrics, b.admin.port)
            read_lines.extend(
                ln for ln in text.splitlines()
                if "storage_read" in ln and not ln.startswith("#")
            )
        assert read_lines, "no storage_read counters on /metrics"
        if fetch_wire_enabled():
            hits = sum(
                float(ln.rsplit(" ", 1)[1])
                for ln in read_lines
                if 'counter="wire_cache_hits"' in ln
            )
            assert hits > 0, (
                f"wire plane served replays without cache hits:\n"
                + "\n".join(read_lines)
            )
    finally:
        await client.close()
        for b in brokers:
            await b.stop()
        shutil.rmtree(tmp, ignore_errors=True)
    print(f"CONSUME-SMOKE-OK mode={mode}")


if __name__ == "__main__":
    asyncio.run(main())
