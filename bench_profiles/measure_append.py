"""In-situ wall timing of the sync leaf functions on the replicated
hot path — sampling attribution is biased for C-heavy lines (SIGPROF
delivery defers across C calls), so this wraps the suspects directly
and reports true wall shares."""

import asyncio
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class T:
    __slots__ = ("name", "n", "tot")

    def __init__(self, name):
        self.name = name
        self.n = 0
        self.tot = 0.0


TIMERS: list[T] = []


def wrap(obj, attr, name=None):
    fn = getattr(obj, attr)
    t = T(name or f"{obj.__name__}.{attr}")
    TIMERS.append(t)

    def timed(*a, **kw):
        t0 = time.perf_counter()
        try:
            return fn(*a, **kw)
        finally:
            t.tot += time.perf_counter() - t0
            t.n += 1

    setattr(obj, attr, timed)
    return t


def wrap_cls(cls, attr, name=None):
    fn = getattr(cls, attr)
    t = T(name or f"{cls.__name__}.{attr}")
    TIMERS.append(t)

    def timed(self, *a, **kw):
        t0 = time.perf_counter()
        try:
            return fn(self, *a, **kw)
        finally:
            t.tot += time.perf_counter() - t0
            t.n += 1

    setattr(cls, attr, timed)
    return t


def main() -> None:
    import tempfile
    import shutil

    from redpanda_tpu.storage import segment as seg_mod
    from redpanda_tpu.storage.batch_cache import BatchCacheIndex
    from redpanda_tpu.models import record as rec_mod
    from redpanda_tpu.raft import types as rt
    from redpanda_tpu.raft.consensus import Consensus
    from redpanda_tpu.storage.log import Log

    wrap_cls(seg_mod.Segment, "append", "segment.append")
    wrap_cls(BatchCacheIndex, "put", "batch_cache.put")
    wrap_cls(rec_mod.RecordBatch, "serialize", "record.serialize")
    wrap(rec_mod.RecordBatch, "deserialize", "record.deserialize")
    wrap(rt.AppendEntriesRequest, "decode", "aer.decode")
    wrap_cls(rt.AppendEntriesRequest, "encode", "aer.encode")
    wrap_cls(rt.AppendEntriesReply, "encode", "rep.encode")
    wrap(rt.AppendEntriesReply, "decode", "rep.decode")
    wrap_cls(Log, "offsets", "log.offsets")
    wrap_cls(Consensus, "handle_append_entries_sync", "follower.handle") if hasattr(
        Consensus, "handle_append_entries_sync"
    ) else None

    async def run():
        import bench
        from redpanda_tpu.kafka.client import KafkaClient
        from redpanda_tpu.models.record import RecordBatchBuilder

        shm = "/dev/shm" if os.path.isdir("/dev/shm") else None
        tmp = tempfile.mkdtemp(prefix="rp_meas_", dir=shm)
        brokers = []
        try:
            brokers = await bench._cluster(tmp, 3)
            client = KafkaClient([b.kafka_advertised for b in brokers])
            n_partitions = 1024
            await client.create_topic(
                "repl", partitions=n_partitions, replication_factor=3
            )
            payload = os.urandom(1008)
            b = RecordBatchBuilder()
            for i in range(64):
                b.add(payload, key=b"k%012d" % i)
            wire = b.build().to_kafka_wire()
            deadline = time.monotonic() + 120.0
            pid = 0
            while pid < n_partitions:
                try:
                    await client.produce_wire("repl", pid, wire, acks=-1)
                    pid += max(1, n_partitions // 16)
                except Exception:
                    if time.monotonic() > deadline:
                        raise
                    await asyncio.sleep(0.25)
            for t in TIMERS:
                t.n = 0
                t.tot = 0.0
            t_end = time.perf_counter() + 6.0
            sent = 0

            async def producer(idx):
                nonlocal sent
                c = KafkaClient([x.kafka_advertised for x in brokers])
                p = idx * (n_partitions // 4)
                try:
                    while time.perf_counter() < t_end:
                        await c.produce_wire("repl", p, wire, acks=-1)
                        sent += 64 * 1024
                        p = (p + 1) % n_partitions
                finally:
                    await c.close()

            t0 = time.perf_counter()
            await asyncio.gather(*(producer(i) for i in range(4)))
            el = time.perf_counter() - t0
            print(f"mbps={sent/el/1e6:.1f} window={el:.1f}s")
            for t in sorted(TIMERS, key=lambda x: -x.tot):
                if t.n:
                    print(
                        f"{t.name:<22} {100*t.tot/el:5.1f}%  n={t.n:<7} "
                        f"mean={1e6*t.tot/t.n:6.1f}us"
                    )
            await client.close()
        finally:
            for br in brokers:
                try:
                    await br.stop()
                except Exception:
                    pass
            shutil.rmtree(tmp, ignore_errors=True)

    asyncio.run(run())


if __name__ == "__main__":
    main()
