"""Profile the 50k-group live heartbeat tick (VERDICT r3 item #2).

Reuses bench._live_tick_async's fixture but cProfiles the steady tick
loop and prints a per-phase breakdown. Run:
    python bench_profiles/profile_tick.py [n_groups]
"""

import asyncio
import cProfile
import io
import os
import pstats
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


async def main(n_groups: int) -> None:
    import tempfile, shutil
    from redpanda_tpu.raft.group_manager import GroupManager
    from redpanda_tpu.rpc.loopback import LoopbackNetwork, LoopbackTransport

    shm = "/dev/shm" if os.path.isdir("/dev/shm") else None
    tmp = tempfile.mkdtemp(prefix="rp_prof_", dir=shm)
    net = LoopbackNetwork()

    def sender(src):
        async def send(dst, method_id, payload, timeout):
            t = LoopbackTransport(net, src, dst)
            return await t.call(method_id, payload, timeout)

        return send

    gms = {}
    try:
        for nid in (0, 1):
            gm = GroupManager(
                node_id=nid,
                data_dir=os.path.join(tmp, f"node_{nid}"),
                send=sender(nid),
                election_timeout_s=3600.0,
                heartbeat_interval_s=3600.0,
            )
            net.register(nid, gm.service)
            gms[nid] = gm
            await gm.start()
        voters = [0, 1]
        t0 = time.monotonic()
        for gid in range(1, n_groups + 1):
            for gm in gms.values():
                await gm.create_group(gid, voters)
        print(f"setup: created {n_groups} groups x2 in {time.monotonic()-t0:.1f}s", flush=True)
        leaders = []
        for gid in range(1, n_groups + 1):
            c = gms[0].get(gid)
            c.arrays.term[c.row] = 0
            c._become_leader()
            leaders.append(c)
        hb = gms[0].heartbeat_manager
        deadline = time.monotonic() + 120.0
        while any(c.commit_index < c.term_start for c in leaders):
            await hb.tick()
            if time.monotonic() > deadline:
                raise TimeoutError("followers never caught up")
            await asyncio.sleep(0)
        import gc

        gc.collect()
        gc.freeze()
        for _ in range(3):
            await hb.tick()

        times = []
        pr = cProfile.Profile()
        pr.enable()
        for _ in range(40):
            t0 = time.perf_counter()
            await hb.tick()
            times.append((time.perf_counter() - t0) * 1e3)
        pr.disable()
        print("tick ms:", [round(t, 2) for t in times], flush=True)
        print(
            f"p50={np.percentile(times,50):.2f} p99={np.percentile(times,99):.2f}",
            flush=True,
        )
        s = io.StringIO()
        pstats.Stats(pr, stream=s).sort_stats("tottime").print_stats(45)
        out = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            f"tick_{n_groups}_cprofile.txt",
        )
        open(out, "w").write(s.getvalue())
        print("saved", out, flush=True)
    finally:
        for gm in gms.values():
            try:
                await gm.stop()
            except Exception:
                pass
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 50000
    asyncio.run(main(n))
