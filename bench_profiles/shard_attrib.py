"""Per-shard attribution for the ssx shard runtime: start a 2-shard
ShardedBroker, produce/fetch across a partition spread, and print
where the work landed (ShardStats counters + shard-table counts).

Run from the repo root:  python bench_profiles/shard_attrib.py
Feeds the attribution table in bench_profiles/SHARDS_AB.md.
"""

import asyncio
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_PARTITIONS = int(os.environ.get("ATTRIB_PARTITIONS", "16"))
N_ROUNDS = int(os.environ.get("ATTRIB_ROUNDS", "50"))
VALUE = b"x" * 512


async def main():
    from redpanda_tpu.app import BrokerConfig
    from redpanda_tpu.kafka.client import KafkaClient
    from redpanda_tpu.ssx.sharded_broker import ShardedBroker

    tmp = tempfile.mkdtemp(dir="/dev/shm" if os.path.isdir("/dev/shm") else None)
    cfg = BrokerConfig(
        node_id=0,
        data_dir=tmp,
        members=[0],
        election_timeout_s=0.3,
        heartbeat_interval_s=0.05,
        enable_admin=False,
    )
    sb = ShardedBroker(cfg, n_shards=2)
    await sb.start()
    assert sb.active, sb.standdown
    c = KafkaClient([("127.0.0.1", sb.kafka_port)])
    try:
        deadline = time.monotonic() + 30
        while True:
            try:
                await c.create_topic(
                    "attrib", partitions=N_PARTITIONS, replication_factor=1
                )
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise
                await asyncio.sleep(0.2)
        # warm every partition (leadership settles), then measure
        for p in range(N_PARTITIONS):
            while True:
                try:
                    await c.produce("attrib", p, [(b"k", VALUE)])
                    break
                except Exception:
                    if time.monotonic() > deadline:
                        raise
                    await asyncio.sleep(0.2)
        t0 = time.monotonic()
        for r in range(N_ROUNDS):
            await asyncio.gather(
                *(
                    c.produce("attrib", p, [(b"k", VALUE)])
                    for p in range(N_PARTITIONS)
                )
            )
        dt = time.monotonic() - t0
        for p in range(N_PARTITIONS):
            await c.fetch("attrib", p, 0)
        n_msgs = N_ROUNDS * N_PARTITIONS
        counts = sb.broker.shard_table.counts()
        stats = await sb.shard_stats()
        print(f"partitions={N_PARTITIONS} rounds={N_ROUNDS} "
              f"msgs={n_msgs} value={len(VALUE)}B wall={dt:.2f}s "
              f"rate={n_msgs / dt:.0f} msg/s")
        print(f"shard_table counts (shard -> partitions): "
              f"{dict(sorted(counts.items()))}")
        print("| shard | partitions | leaders | produce_reqs | "
              "produce_bytes | fetch_reqs | frontend_conns | frontend_frames |")
        print("|---|---|---|---|---|---|---|---|")
        for s in stats:
            print(
                f"| {s.shard} | {s.partitions} | {s.leaders} "
                f"| {s.produce_reqs} | {s.produce_bytes} "
                f"| {s.fetch_reqs} | {s.frontend_conns} "
                f"| {s.frontend_frames} |"
            )
    finally:
        await c.close()
        await sb.stop()
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    asyncio.run(main())
