"""Profile the replicated acks=all hot path (VERDICT r4 item #1).

Boots the same 3-broker / N-partition cluster as bench.py's
`replicated` config, but:
  - cProfile wraps ONLY the measurement window (setup excluded),
  - GC pauses are tracked via gc.callbacks (gen2 pause = p99 suspect),
  - per-produce latency goes into a histogram so the cliff is visible.

Run:  python -u bench_profiles/profile_replicated.py [partitions] [secs]
"""

import asyncio
import cProfile
import gc
import io
import os
import pstats
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


async def main(n_partitions: int, duration_s: float, tag: str) -> None:
    import shutil

    import bench
    from redpanda_tpu.kafka.client import KafkaClient
    from redpanda_tpu.models.record import RecordBatchBuilder

    n_producers = 4
    batch_records = 64
    record_bytes = 1024
    acks = int(os.environ.get("RP_PROF_ACKS", "-1"))
    shm = "/dev/shm" if os.path.isdir("/dev/shm") else None
    tmp = tempfile.mkdtemp(prefix="rp_prof_", dir=shm)
    brokers = []
    client = None
    try:
        t0 = time.monotonic()
        brokers = await bench._cluster(tmp, 3)
        client = KafkaClient([b.kafka_advertised for b in brokers])
        await client.create_topic(
            "repl", partitions=n_partitions, replication_factor=3
        )
        payload = os.urandom(record_bytes - 16)
        builder = RecordBatchBuilder()
        for i in range(batch_records):
            builder.add(payload, key=b"k%012d" % i)
        wire = builder.build().to_kafka_wire()
        deadline = time.monotonic() + 120.0
        pid_probe = 0
        while pid_probe < n_partitions:
            try:
                await client.produce_wire("repl", pid_probe, wire, acks=-1)
                pid_probe += max(1, n_partitions // 16)
            except Exception:
                if time.monotonic() > deadline:
                    raise
                await asyncio.sleep(0.25)
        print(f"setup done in {time.monotonic()-t0:.1f}s", flush=True)

        if os.environ.get("RP_PROF_GCFREEZE", "0") == "1":
            # candidate fix for the gen2 p99 cliff: move the settled
            # broker object graph out of the collector (same trick the
            # live-tick bench applies)
            gc.collect()
            gc.freeze()
            print("gc.freeze applied after setup", flush=True)
        from redpanda_tpu.utils import spans as _spans

        _spans.reset()  # drop setup-phase accumulation (elections etc.)
        # GC pause tracking
        gc_pauses: list[tuple[int, float]] = []
        gc_t0 = [0.0]

        def gc_cb(phase, info):
            if phase == "start":
                gc_t0[0] = time.perf_counter()
            else:
                gc_pauses.append(
                    (info["generation"], (time.perf_counter() - gc_t0[0]) * 1e3)
                )

        gc.callbacks.append(gc_cb)

        lat_ms: list[float] = []
        sent = [0]
        t_end = time.perf_counter() + duration_s

        async def producer(idx: int) -> None:
            c = KafkaClient([b.kafka_advertised for b in brokers])
            pid = idx * (n_partitions // n_producers)
            try:
                while time.perf_counter() < t_end:
                    t0 = time.perf_counter()
                    await c.produce_wire("repl", pid, wire, acks=acks)
                    lat_ms.append((time.perf_counter() - t0) * 1e3)
                    sent[0] += batch_records * record_bytes
                    pid = (pid + 1) % n_partitions
            finally:
                await c.close()

        use_profile = os.environ.get("RP_PROF_CPROFILE", "0") == "1"
        use_sampler = os.environ.get("RP_PROF_SAMPLE", "0") == "1"
        sampler = None
        if use_sampler:
            if os.environ.get("RP_PROF_PHASES", "0") == "1":
                from sampler import PhaseSampler as Sampler
            elif os.environ.get("RP_PROF_STACKS", "0") == "1":
                from sampler import StackSampler as Sampler
            else:
                from sampler import Sampler

            sampler = Sampler()
            sampler.start()
        pr = cProfile.Profile()
        t0 = time.perf_counter()
        if use_profile:
            pr.enable()
        await asyncio.gather(*(producer(i) for i in range(n_producers)))
        if use_profile:
            pr.disable()
        wall = time.perf_counter() - t0
        if sampler is not None:
            sampler.stop()
            print(sampler.report(35), flush=True)
        gc.callbacks.remove(gc_cb)

        mbps = sent[0] / wall / 1e6
        arr = np.array(lat_ms)
        print(
            f"partitions={n_partitions} mbps={mbps:.1f} rounds={len(lat_ms)} "
            f"p50={np.percentile(arr,50):.2f}ms p90={np.percentile(arr,90):.2f}ms "
            f"p99={np.percentile(arr,99):.2f}ms max={arr.max():.2f}ms",
            flush=True,
        )
        hist, edges = np.histogram(
            arr, bins=[0, 2, 5, 10, 20, 50, 100, 200, 400, 10000]
        )
        print("latency histogram (ms buckets):", flush=True)
        for h, lo, hi in zip(hist, edges, edges[1:]):
            print(f"  [{lo:>5.0f},{hi:>5.0f}): {h}", flush=True)
        gen2 = [p for g, p in gc_pauses if g == 2]
        gen_all = [p for _, p in gc_pauses]
        print(
            f"gc: {len(gc_pauses)} collections, "
            f"gen2={len(gen2)} (max {max(gen2) if gen2 else 0:.1f}ms), "
            f"max_any={max(gen_all) if gen_all else 0:.1f}ms "
            f"sum={sum(gen_all):.1f}ms",
            flush=True,
        )
        # t_end was computed before task startup: re-derive effective
        # duration from the latency stream when reporting
        here = os.path.dirname(os.path.abspath(__file__))
        if use_profile:
            for sort, name in (("tottime", "tottime"), ("cumulative", "cum")):
                s = io.StringIO()
                pstats.Stats(pr, stream=s).sort_stats(sort).print_stats(50)
                path = os.path.join(
                    here, f"replicated_{tag}_{n_partitions}p_{name}.txt"
                )
                open(path, "w").write(s.getvalue())
                print("saved", path, flush=True)
        from redpanda_tpu.utils import spans

        rep = spans.report()
        if rep:
            print("span report:", flush=True)
            print(rep, flush=True)
    finally:
        if client is not None:
            try:
                await client.close()
            except Exception:
                pass
        for b in brokers:
            try:
                await b.stop()
            except Exception:
                pass
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    parts = int(sys.argv[1]) if len(sys.argv) > 1 else 1024
    secs = float(sys.argv[2]) if len(sys.argv) > 2 else 4.0
    tag = sys.argv[3] if len(sys.argv) > 3 else "before"
    asyncio.run(main(parts, secs, tag))
