"""Signal-based sampling profiler (py-spy is not in this image and
cProfile's tracing overhead collapses the 1-core broker workload to
~zero throughput — r4 measured 4 rounds/2s under cProfile vs ~1800
without). SIGPROF fires on CPU time, the handler walks the current
frame stack; aggregate cost is ~0.1% at 5 ms intervals and the
workload runs at full speed.

Usage:
    from sampler import Sampler
    s = Sampler(); s.start()
    ... workload ...
    s.stop(); print(s.report(25))
"""

from __future__ import annotations

import collections
import signal
import sys


class Sampler:
    def __init__(self, interval_s: float = 0.005):
        self.interval = interval_s
        self.samples: collections.Counter = collections.Counter()
        self.total = 0
        self._old = None

    def _handler(self, signum, frame):
        self.total += 1
        # leaf-ward attribution: innermost 3 frames name the hot spot
        parts = []
        f = frame
        depth = 0
        while f is not None and depth < 3:
            co = f.f_code
            fn = co.co_filename
            short = fn[fn.rfind("/", 0, fn.rfind("/")) + 1 :]
            parts.append(f"{short}:{co.co_name}:{f.f_lineno}")
            f = f.f_back
            depth += 1
        self.samples[" < ".join(parts)] += 1

    def start(self) -> None:
        self._old = signal.signal(signal.SIGPROF, self._handler)
        signal.setitimer(signal.ITIMER_PROF, self.interval, self.interval)

    def stop(self) -> None:
        signal.setitimer(signal.ITIMER_PROF, 0, 0)
        if self._old is not None:
            signal.signal(signal.SIGPROF, self._old)

    def report(self, top: int = 30) -> str:
        out = [f"samples: {self.total} ({self.total * self.interval:.1f}s CPU)"]
        for stack, n in self.samples.most_common(top):
            out.append(f"{n:>6} {100*n/max(1,self.total):5.1f}%  {stack}")
        return "\n".join(out)


class StackSampler(Sampler):
    """Records the full folded stack per sample; report() prints
    inclusive per-function percentages (flamegraph column view)."""

    def _handler(self, signum, frame):
        self.total += 1
        parts = []
        f = frame
        while f is not None:
            co = f.f_code
            fn = co.co_filename
            short = fn[fn.rfind("/", 0, fn.rfind("/")) + 1 :]
            parts.append(f"{short}:{co.co_name}")
            f = f.f_back
        self.samples[tuple(parts)] += 1

    def report(self, top: int = 40) -> str:
        import collections

        incl: collections.Counter = collections.Counter()
        for stack, n in self.samples.items():
            for fr in set(stack):
                incl[fr] += n
        out = [f"samples: {self.total} ({self.total * self.interval:.1f}s CPU)"]
        out.append("-- inclusive % (function appears anywhere in stack) --")
        for fr, n in incl.most_common(top):
            out.append(f"{n:>6} {100*n/max(1,self.total):5.1f}%  {fr}")
        return "\n".join(out)


class PhaseSampler(Sampler):
    """Buckets each sample by the outermost recognizable subsystem
    frame instead of the innermost 3 — answers "which phase of the
    round burns the CPU" rather than "which line"."""

    MARKERS = [
        ("_do_append_entries", "follower:append_entries"),
        ("install_snapshot", "follower:install_snapshot"),
        ("_flush_round", "leader:replicate_batcher"),
        ("_dispatch_append", "leader:dispatch_append"),
        ("_flush_rounds", "leader:append_aggregator"),
        ("heartbeat", "raft:heartbeat"),
        ("try_election", "raft:election"),
        ("handle_produce", "kafka:produce_handler"),
        ("handle_fetch", "kafka:fetch_handler"),
        ("handle", "kafka:other_handler"),
        ("produce_wire", "client:produce"),
        ("write_loop", "kafka:write_loop"),
        ("read_loop", "kafka:read_loop"),
        ("dispatch", "rpc:dispatch"),
        ("call", "rpc:call"),
        ("_tick", "background:tick"),
        ("_run_once", "asyncio:loop"),
    ]

    def _handler(self, signum, frame):
        self.total += 1
        names = []
        f = frame
        while f is not None:
            names.append(f.f_code.co_name)
            f = f.f_back
        # innermost match wins: the deepest recognizable subsystem
        # frame owns the sample (loopback RPC runs server handlers
        # inline under the caller's stack, so outermost scanning
        # mis-charges follower work to the leader)
        label = None
        for name in names:
            for marker, lab in self.MARKERS:
                if name == marker:
                    label = lab
                    break
            if label is not None and not label.startswith("asyncio"):
                break
        self.samples[label or "other:" + names[0]] += 1
