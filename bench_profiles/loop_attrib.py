"""Per-coroutine event-loop time attribution.

Every asyncio callback — task steps and plain call_soon callbacks —
funnels through `asyncio.events.Handle._run`. LoopAttributor patches
that one method to time each invocation and bucket it by the owning
Task's coroutine `__qualname__` (plain callbacks bucket under their
own qualname). That answers "where do the event loop's microseconds
go per replicated round?" without a sampling profiler's blind spots
or cProfile's 2-3x slowdown: overhead is one perf_counter_ns pair per
callback (~0.3 µs), small against the ~10 µs median task step.

Usage (what `bench.py --attrib` / RP_BENCH_ATTRIB=1 does):

    from bench_profiles.loop_attrib import LoopAttributor
    attr = LoopAttributor()
    attr.start()          # patch in (idempotent)
    ... run the measured window ...
    attr.stop()           # restore the original Handle._run
    print(attr.table(rounds=n_rounds))

The table is sorted by total time and reports per-round µs so two runs
with different window lengths compare directly — the before/after
attribution tables in bench_profiles/ are produced this way.
"""

from __future__ import annotations

import asyncio
import asyncio.events
import time
from collections import defaultdict


class LoopAttributor:
    def __init__(self) -> None:
        self.ns: dict[str, int] = defaultdict(int)
        self.calls: dict[str, int] = defaultdict(int)
        self.max_ns: dict[str, int] = defaultdict(int)
        self._orig = None

    def start(self) -> None:
        if self._orig is not None:
            return
        self._orig = orig = asyncio.events.Handle._run
        ns = self.ns
        calls = self.calls
        max_ns = self.max_ns
        perf = time.perf_counter_ns
        Task = asyncio.Task

        def _run(handle):
            cb = handle._callback
            owner = getattr(cb, "__self__", None)
            if isinstance(owner, Task):
                try:
                    label = owner.get_coro().__qualname__
                except Exception:
                    label = "<task>"
            else:
                label = getattr(cb, "__qualname__", None) or repr(cb)
            t0 = perf()
            try:
                return orig(handle)
            finally:
                dt = perf() - t0
                ns[label] += dt
                calls[label] += 1
                if dt > max_ns[label]:
                    max_ns[label] = dt

        asyncio.events.Handle._run = _run

    def stop(self) -> None:
        if self._orig is not None:
            asyncio.events.Handle._run = self._orig
            self._orig = None

    def reset(self) -> None:
        self.ns.clear()
        self.calls.clear()
        self.max_ns.clear()

    def table(self, rounds: int | None = None, top: int = 24) -> str:
        """Formatted per-coroutine attribution, sorted by total time.
        With `rounds` (e.g. completed produce rounds in the window) a
        µs/round column normalizes across window lengths."""
        rows = sorted(self.ns.items(), key=lambda kv: -kv[1])[:top]
        total_ns = sum(self.ns.values())
        head = (
            f"{'coroutine':<52} {'calls':>9} {'total_ms':>9} "
            f"{'us/call':>8} {'max_ms':>7}"
        )
        if rounds:
            head += f" {'us/round':>9}"
        lines = [head, "-" * len(head)]
        for label, t in rows:
            c = self.calls[label]
            line = (
                f"{label[:52]:<52} {c:>9} {t / 1e6:>9.1f} "
                f"{t / c / 1e3:>8.1f} {self.max_ns[label] / 1e6:>7.2f}"
            )
            if rounds:
                line += f" {t / rounds / 1e3:>9.1f}"
            lines.append(line)
        foot = f"{'TOTAL':<52} {sum(self.calls.values()):>9} {total_ns / 1e6:>9.1f}"
        if rounds:
            foot += f" {'':>8} {total_ns / rounds / 1e3:>9.1f}"
        lines.append("-" * len(head))
        lines.append(foot)
        return "\n".join(lines)
